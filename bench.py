"""Benchmark driver: single-chip radix join throughput on real TPU.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Workload: the reference's canonical per-node join scaled to one chip —
16M ⋈ 16M dense unique uint32 keys (BASELINE.md config #2; the reference runs
20M ⋈ 20M per node, main.cpp:70-71).  Correctness is asserted against the
unique-key oracle before timing.

Timing methodology: the TPU in this environment sits behind a tunnel where
``jax.block_until_ready`` returns before execution finishes and a host
round-trip costs ~30-125ms.  So each candidate is jitted end-to-end, timed
over enough dispatches that compute dominates, and the clock stops on a real
host readback (np.asarray) of the final result.

vs_baseline: the reference publishes no numbers (BASELINE.md — published {}),
so the denominator is 1e9 tuples/sec/accelerator, a nominal figure for the
reference-era GPU build/probe kernels (sm_60-class, eth.cu) on this workload;
vs_baseline >= 1.0 therefore means beating reference-class per-accelerator
throughput.

``--check-regress BASELINE.json`` runs the observability regression gate
as a post-step: the fresh result's numeric tags are compared against the
baseline (tools_check_regress.py semantics), the delta table goes to
stderr, and the process exits 1 on any regression — the JSON line above
is printed either way.
"""

import contextlib
import glob
import json
import math
import os
import re
import sys
import time

import numpy as np


def _time_amortized(fn, args, iters=20):
    """Seconds/iteration: ``iters`` async dispatches closed by one host
    readback (the only reliable sync through the tunnel)."""
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / iters


class _BackendDown(ConnectionError):
    """One backend probe failed (tunnel down / device init error)."""


#: cross-run ledger destination (--ledger-dir / $TPU_RADIX_LEDGER_DIR);
#: set by main(), consumed by _ledger_append after every BENCH JSON line
_LEDGER_DIR = None


def _ledger_append(result):
    """Mirror the BENCH result line into the cross-run telemetry ledger
    (observability/ledger.py) so tools_profile_fit.py can fit constants
    from live rounds without the report-time backfill.  Off unless a
    ledger dir is configured; a ledger failure never fails the bench."""
    if not _LEDGER_DIR:
        return
    try:
        from tpu_radix_join.observability.ledger import Ledger, bench_payload
        payload = bench_payload(result)
        if payload is not None:
            led = Ledger(_LEDGER_DIR)
            led.append("bench", payload)
            print(f"note: ledger row -> {led.path}", file=sys.stderr)
    except Exception as e:   # noqa: BLE001 — telemetry must not sink a bench
        print(f"note: ledger append failed: {e!r}", file=sys.stderr)


def _planned_strategy(size, iters):
    """What the planner would run for the bench workload (pure host math —
    needs no live backend).  Stamped into the BENCH json on success AND on
    a backend-unavailable exit, so even a round whose capture is otherwise
    empty records which discipline the round intended to measure."""
    try:
        from tpu_radix_join.planner import Workload, load_profile, plan_join
        plan, _ = plan_join(load_profile(), Workload(
            r_tuples=size, s_tuples=size, key_bound=size,
            num_nodes=1, repeats=iters))
        return {"strategy": plan.strategy,
                "predicted_ms": plan.predicted_ms,
                "profile": plan.profile_name}
    except Exception as e:       # a planner bug must not sink the bench
        return {"strategy": "unknown", "error": repr(e)}


def _wait_for_backend(planned=None, forensics_dir=None):
    """Probe the device backend, retrying a downed tunnel for up to
    BENCH_TUNNEL_WAIT_SEC (default 20 min) before giving up.

    A downed axon tunnel makes jax.devices() block on a *native* futex that
    a SIGALRM Python handler can never interrupt; probe in a child process
    with a hard per-attempt timeout.  Two rounds of BENCH_r0*.json rc=2
    showed a one-shot 120s window loses against tunnel flakiness, so the
    bench now rides out transient outages itself instead of leaving the
    round's official capture empty.  The retry loop is a
    robustness.retry.RetryPolicy whose ``max_elapsed_s`` is the budget —
    the same deadline discipline the rest of the resilience layer uses.
    """
    import subprocess

    from tpu_radix_join.robustness.retry import (RetriesExhausted,
                                                 RetryPolicy, execute)

    budget = float(os.environ.get("BENCH_TUNNEL_WAIT_SEC", "1200"))
    probe_timeout = float(os.environ.get("BENCH_PROBE_TIMEOUT_SEC", "120"))
    attempts = [0]

    def probe():
        attempts[0] += 1
        try:
            # sitecustomize locks the platform default at import; the child
            # re-applies any JAX_PLATFORMS override the same way the parent
            p = subprocess.run(
                [sys.executable, "-c",
                 "import os, jax\n"
                 "p = os.environ.get('JAX_PLATFORMS')\n"
                 "p and jax.config.update('jax_platforms', p)\n"
                 "print(jax.devices()[0])"],
                capture_output=True, text=True, timeout=probe_timeout)
        except subprocess.TimeoutExpired:
            raise _BackendDown(f"probe hung {probe_timeout:.0f}s "
                               f"(tunnel down)")
        if p.returncode != 0:
            raise _BackendDown((p.stderr.strip().splitlines() or ["?"])[-1])
        print(f"note: device: {p.stdout.strip()} "
              f"(probe attempt {attempts[0]})", file=sys.stderr)

    def on_retry(attempt, err, delay):
        print(f"note: backend probe {attempt + 1} failed ({err}); "
              f"retrying in {delay:.0f}s", file=sys.stderr)

    # attempts effectively unbounded; the elapsed budget is the terminator
    policy = RetryPolicy(max_attempts=1 << 20, base_delay_s=15.0,
                         multiplier=1.5, max_delay_s=60.0, jitter=0.1,
                         max_elapsed_s=budget)
    try:
        execute(probe, policy, retryable=(_BackendDown,),
                on_retry=on_retry, label="backend_probe")
    except RetriesExhausted as e:
        from tpu_radix_join.robustness.retry import BACKEND_UNAVAILABLE
        print(f"ERROR: device backend unavailable after {e.attempts} probes "
              f"over {budget:.0f}s: {e.last_error}", file=sys.stderr)
        # a machine-readable capture instead of a bare rc=2: the round's
        # BENCH artifact records what failed and what would have run
        print(json.dumps({
            "metric": "single_chip_join_throughput",
            "value": 0.0,
            "unit": "tuples/sec",
            "vs_baseline": 0.0,
            "failure_class": BACKEND_UNAVAILABLE,
            "planned_strategy": (planned or {}).get("strategy", "unknown"),
            "planned": planned,
            "probe_attempts": e.attempts,
            "wait_budget_s": budget,
            "last_error": str(e.last_error),
        }))
        if forensics_dir:
            # the death the bundles were invented for: rounds 3-5 left only
            # a bare rc=2 behind when the tunnel died under the bench
            try:
                from tpu_radix_join.observability.postmortem import \
                    write_bundle
                path = write_bundle(
                    forensics_dir, None, reason="backend_unavailable",
                    failure_class=BACKEND_UNAVAILABLE,
                    extra={"probe_attempts": e.attempts,
                           "wait_budget_s": budget,
                           "last_error": str(e.last_error),
                           "planned": planned})
                print(f"note: forensics bundle {path}", file=sys.stderr)
            except Exception as be:    # noqa: BLE001 — forensics must not
                print(f"note: bundle write failed: {be!r}",   # mask
                      file=sys.stderr)
        sys.exit(2)


def _sort_bandwidth_gbps(probe_dt_s, size):
    """Achieved HBM GB/s of the sort stage against the external-sort traffic
    lower bound (PERF_NOTES "sort floor": ``1 + ceil(log2(union/V))`` passes
    of read+write over the packed union, V = 4M VMEM-resident elements).

    Prefers the trace-derived per-iter sort time from the newest committed
    ``breakdown.json`` (exp_trace_pipeline) when one matches this workload;
    falls back to the measured probe time (an upper bound on the sort, so a
    lower bound on GB/s).  Returns (gbps, source_label).
    """
    union = 2 * size
    vmem_elems = 4 << 20
    passes = 1 + max(0, math.ceil(math.log2(union / vmem_elems)))
    min_traffic_bytes = passes * 2 * union * 4       # r+w, 4 B/element
    sort_s, src = probe_dt_s, "probe_upper_bound"
    here = os.path.dirname(os.path.abspath(__file__))
    from tpu_radix_join.performance.trace import _is_device_plane

    def round_num(path):
        m = re.search(r"chip_r(\d+)", path)
        return int(m.group(1)) if m else -1

    for path in sorted(glob.glob(
            os.path.join(here, "artifacts", "chip_r*", "trace_*",
                         "breakdown.json")), key=round_num, reverse=True):
        try:
            with open(path) as f:
                bd = json.load(f)
        except (OSError, ValueError):
            continue
        # host-plane artifacts (CPU smoke runs) sum nested Python frames,
        # not device time — same refusal as measurements.py's CTOTAL guard;
        # non-sort disciplines (e.g. the two-level trace) carry a different
        # program's sort time and are skipped (absent key = legacy sort)
        if (bd.get("size") == size and bd.get("sort_share")
                and bd.get("discipline", "sort") == "sort"
                and _is_device_plane(bd.get("plane", ""))):
            sort_s = bd["busy_us"] * bd["sort_share"] / bd["iters"] / 1e6
            src = os.path.relpath(path, here)
            break
    return min_traffic_bytes / sort_s / 1e9, src


def _run_chaos(runs, base_seed=0, forensics_dir=None):
    """``--chaos N``: CPU soak of N seeded fault schedules with verification
    on.  Prints one outcome line per run and a JSON summary; a violating
    schedule is shrunk to a minimal repro written under artifacts/chaos/,
    with a forensics bundle (observability/postmortem.py) named in the
    repro.  Exit 0 iff no violations."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)
    from tpu_radix_join.robustness import chaos

    def show(out):
        cls = f" class={out.failure_class}" if out.failure_class else ""
        print(f"[CHAOS] seed={out.schedule.seed} {out.status}{cls} "
              f"arms={[s for s, _ in out.schedule.arms]}")

    here = os.path.dirname(os.path.abspath(__file__))
    bundle_dir = forensics_dir or os.path.join(here, "artifacts", "chaos",
                                               "forensics")
    runner = chaos.ChaosRunner(verify="check", bundle_dir=bundle_dir)
    outcomes, summary = chaos.soak(runs, base_seed=base_seed, runner=runner,
                                   on_outcome=show)
    for out in outcomes:
        if out.status != chaos.VIOLATION:
            continue
        shrunk = chaos.shrink(
            out.schedule,
            lambda s: runner.run(s).status == chaos.VIOLATION)
        repro = runner.run(shrunk)
        here = os.path.dirname(os.path.abspath(__file__))
        rdir = os.path.join(here, "artifacts", "chaos")
        os.makedirs(rdir, exist_ok=True)
        path = os.path.join(rdir, f"repro_seed{shrunk.seed}.json")
        print("[CHAOS] repro " + chaos.write_repro(repro, path))
        print(f"[CHAOS] repro written to {path}")
        if repro.bundle:
            print(f"[CHAOS] forensics bundle {repro.bundle}")
    print("[CHAOS] " + json.dumps(summary, sort_keys=True))
    return 0 if summary["violations"] == 0 else 1


def _run_grid_bench(check_baseline=None):
    """``--grid-bench``: A/B of the out-of-core grid engines (ops/chunked.py
    ``--grid-pipeline off`` vs ``on``) on a 4x4 chunk grid, CPU-sized like
    ``--chaos`` — it validates the pipeline's overlap win and work counters
    (GRIDPAIRS/PREFETCH/SORTREUSE), not chip throughput.  Prints one BENCH
    JSON line whose headline ``value`` is pipelined pairs/sec and whose
    ``vs_baseline``/``speedup`` is pipelined-over-synchronous, so
    tools_check_regress.py fails loudly when the pipeline regresses."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.data.streaming import stream_chunks_device
    from tpu_radix_join.ops.chunked import chunked_join_grid
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.performance.measurements import (GRIDPAIRS, PREFETCH,
                                                         SORTREUSE)

    chunk = 1 << 15                  # 32K-tuple chunks -> 4x4 grid
    size = chunk * 4
    inner = Relation(size, 1, "unique", seed=11)
    outer = Relation(size, 1, "unique", seed=12)
    expected = inner.expected_matches(outer)

    def run(mode, meas=None):
        # inner streamed once, outer regenerated per row (the out-of-core
        # shape): generation overlap is part of what the pipeline hides
        t0 = time.perf_counter()
        total = chunked_join_grid(
            stream_chunks_device(inner, 0, chunk),
            lambda: stream_chunks_device(outer, 0, chunk),
            chunk, measurements=meas, pipeline=mode)
        return total, time.perf_counter() - t0

    stats = {}
    for mode in ("off", "on"):
        run(mode)                    # warmup: compiles + thread spinup
        meas = Measurements(node_id=0, num_nodes=1)
        total, wall = run(mode, meas)
        if expected is not None and total != expected:
            print(f"ERROR: grid total {total} != oracle {expected} "
                  f"(pipeline={mode})", file=sys.stderr)
            sys.exit(3)
        pairs = meas.counters.get(GRIDPAIRS, 0)
        stats[mode] = {"wall_s": wall, "pairs": pairs,
                       "pairs_per_sec": pairs / wall if wall > 0 else 0.0,
                       "prefetch": meas.counters.get(PREFETCH, 0),
                       "sortreuse": meas.counters.get(SORTREUSE, 0)}
        print(f"note: pipeline={mode}: {wall*1e3:.1f} ms, "
              f"{stats[mode]['pairs_per_sec']:.2f} pairs/s, "
              f"PREFETCH={stats[mode]['prefetch']} "
              f"SORTREUSE={stats[mode]['sortreuse']}", file=sys.stderr)
    speedup = (stats["on"]["pairs_per_sec"]
               / max(stats["off"]["pairs_per_sec"], 1e-9))
    result = {
        "metric": "grid_join_pipeline",
        "value": round(stats["on"]["pairs_per_sec"], 3),
        "unit": "pairs/sec",
        "vs_baseline": round(speedup, 4),
        "speedup": round(speedup, 4),
        "pairs_per_sec_sync": round(stats["off"]["pairs_per_sec"], 3),
        "pairs_per_sec_pipelined": round(stats["on"]["pairs_per_sec"], 3),
        "gridpairs": stats["on"]["pairs"],
        "prefetch": stats["on"]["prefetch"],
        "sortreuse": stats["on"]["sortreuse"],
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_exchange_bench(check_baseline=None):
    """``--exchange-bench``: A/B of the shuffle wire format — raw 8 B/tuple
    lanes over a fused all_to_all versus the bit-packed codec
    (data/tuples.py WireSpec) over a 4-group staged exchange
    (parallel/window.py) — on an 8-way host-CPU mesh with full verification
    on.  Both arms must be oracle-exact (exit 3 otherwise); the BENCH
    headline ``value`` is the wire *reduction* ratio (raw bytes/tuple over
    packed bytes/tuple, higher is better), and the footprint tags
    (``bytes_per_tuple``, ``peak_exchange_bytes``, ``wirebytes``) gate
    lower-is-better under tools_check_regress.py."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.operators.hash_join import HashJoin
    from tpu_radix_join.performance import Measurements

    nodes, per_node = 8, 1 << 17
    inner = Relation(per_node * nodes, nodes, "unique", seed=21)
    outer = Relation(per_node * nodes, nodes, "unique", seed=22)
    expected = inner.expected_matches(outer)

    arms = (("off", dict(exchange_codec="off", exchange_stages=1)),
            ("pack", dict(exchange_codec="pack", exchange_stages=4)))
    stats = {}
    for name, kw in arms:
        meas = Measurements(node_id=0, num_nodes=nodes)
        eng = HashJoin(JoinConfig(num_nodes=nodes, verify="check", **kw),
                       measurements=meas)
        eng.join(inner, outer)              # warmup: mesh + compile
        t0 = time.perf_counter()
        res = eng.join(inner, outer)
        wall = time.perf_counter() - t0
        if not res.ok:
            print(f"ERROR: verification failed (codec={name}): "
                  f"{res.failure}", file=sys.stderr)
            sys.exit(3)
        if expected is not None and res.matches != expected:
            print(f"ERROR: matches {res.matches} != oracle {expected} "
                  f"(codec={name})", file=sys.stderr)
            sys.exit(3)
        xs = meas.meta.get("exchange_plan")
        if not xs:
            print(f"ERROR: no exchange_plan stamped (codec={name})",
                  file=sys.stderr)
            sys.exit(3)
        stats[name] = dict(xs, wall_s=wall)
        print(f"note: codec={name}: {xs['bytes_per_tuple']:.3f} B/tuple, "
              f"peak {xs['peak_exchange_bytes']} B/collective, "
              f"wire {xs['wire_bytes']} B, stages={xs['stages']}, "
              f"{wall*1e3:.1f} ms wall", file=sys.stderr)

    off, pack = stats["off"], stats["pack"]
    reduction = off["bytes_per_tuple"] / max(pack["bytes_per_tuple"], 1e-9)
    peak_speedup = (off["peak_exchange_bytes"]
                    / max(pack["peak_exchange_bytes"], 1))
    result = {
        "metric": "exchange_wire_reduction",
        "value": round(reduction, 4),
        "unit": "raw_over_packed_bytes",
        "vs_baseline": round(reduction, 4),
        "bytes_per_tuple": round(pack["bytes_per_tuple"], 4),
        "bytes_per_tuple_raw": round(off["bytes_per_tuple"], 4),
        "peak_exchange_bytes": pack["peak_exchange_bytes"],
        "peak_exchange_bytes_raw": off["peak_exchange_bytes"],
        "peak_speedup": round(peak_speedup, 2),
        "wirebytes": pack["wire_bytes"],
        "wirebytes_raw": off["wire_bytes"],
        "pack_ratio_pct": pack["pack_ratio_pct"],
        "stages": pack["stages"],
        "wall_off_ms": round(off["wall_s"] * 1e3, 1),
        "wall_pack_ms": round(pack["wall_s"] * 1e3, 1),
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_partition_bench(check_baseline=None, size=1 << 24):
    """``--partition-bench``: A/B of the destination-grouping engine —
    the sort-based block scatter (``sort_kv_unstable`` over every lane)
    versus the fused Pallas histogram→scan→scatter partition kernel
    (ops/pallas/partition.py, interpreted on this host mesh) — at ``size``
    keys over 8 destination blocks.

    Correctness first: two full 8-way host-CPU joins (one per impl) with
    ``verify=check`` must be oracle-exact (exit 3 otherwise) so the timing
    legs can never bless a wrong kernel.  The BENCH headline ``value`` is
    the wall speedup (sort over fused, higher is better); the per-arm
    walls land as lower-is-better tags and ``partition_unit_ms`` is the
    reduced ms/Mtuple/pass constant the profile fitter recovers
    (planner/calibrate.py BENCH_PARTITION_METRIC)."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    import jax
    import jax.numpy as jnp
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.operators.hash_join import HashJoin
    from tpu_radix_join.ops.pallas.partition import partition_slots_pallas
    from tpu_radix_join.ops.radix import scatter_to_blocks
    from tpu_radix_join.performance import Measurements

    nodes, per_node = 8, 1 << 15
    inner = Relation(per_node * nodes, nodes, "unique", seed=31)
    outer = Relation(per_node * nodes, nodes, "unique", seed=32)
    expected = inner.expected_matches(outer)
    for impl in ("sort", "pallas_interpret"):
        meas = Measurements(node_id=0, num_nodes=nodes)
        eng = HashJoin(JoinConfig(num_nodes=nodes, verify="check",
                                  partition_impl=impl), measurements=meas)
        res = eng.join(inner, outer)
        if not res.ok:
            print(f"ERROR: verification failed (partition_impl={impl}): "
                  f"{res.failure}", file=sys.stderr)
            sys.exit(3)
        if expected is not None and res.matches != expected:
            print(f"ERROR: matches {res.matches} != oracle {expected} "
                  f"(partition_impl={impl})", file=sys.stderr)
            sys.exit(3)
        print(f"note: join oracle-exact (partition_impl={impl}, "
              f"{per_node * nodes} tuples/side)", file=sys.stderr)

    # timing legs: the isolated scatter at bench scale — the same
    # (batch, dest) -> blocks transform both engines run inside shard_map,
    # jitted standalone so the A/B measures the grouping discipline alone
    n = size
    cap = (n // nodes) * 3 // 2          # uniform dest + 1.5x slack
    rng = np.random.default_rng(7)
    dest = jnp.asarray(rng.integers(0, nodes, n, dtype=np.uint32))
    batch = TupleBatch(key=jnp.asarray(
        rng.integers(0, 1 << 31, n, dtype=np.uint32)),
        rid=jnp.arange(n, dtype=jnp.uint32))

    def arm(impl):
        fn = jax.jit(lambda b, d: scatter_to_blocks(
            b, d, nodes, cap, "inner", impl=impl)[0].key)
        return _time_amortized(fn, (batch, dest), iters=2) * 1e3

    sort_wall = arm("sort")
    fused_wall = arm("pallas_interpret")
    kernel_fn = jax.jit(lambda d: partition_slots_pallas(
        d, num_groups=nodes, capacity=cap, interpret=True)[0])
    kernel_wall = _time_amortized(kernel_fn, (dest,), iters=2) * 1e3
    unit = kernel_wall / (2.0 * n / 1e6)
    speedup = sort_wall / max(fused_wall, 1e-9)
    print(f"note: {n} keys -> {nodes} blocks: sort {sort_wall:.0f} ms, "
          f"fused {fused_wall:.0f} ms (kernel {kernel_wall:.0f} ms), "
          f"speedup {speedup:.2f}x, unit {unit:.4f} ms/Mtuple/pass",
          file=sys.stderr)

    result = {
        "metric": "partition_fused_speedup",
        "value": round(speedup, 3),
        "unit": "sort_over_fused_wall",
        "vs_baseline": round(speedup, 3),
        "size": n,
        "num_blocks": nodes,
        "partition_ms": round(fused_wall, 1),
        "partition_kernel_ms": round(kernel_wall, 1),
        "partition_sort_ms": round(sort_wall, 1),
        "partition_unit_ms": round(unit, 4),
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_sort_bench(check_baseline=None, size=1 << 18):
    """``--sort-bench``: A/B of the flat-sort engine — ``lax.sort`` (the
    XLA emitter) versus the Pallas LSD radix sort
    (ops/pallas/radix_sort.py, interpreted on this host) — across
    key-bound widths and 1/2/3-lane tuples.

    Correctness first, twice over: (1) every (lanes, bound) cell of a
    small sweep must be oracle-exact against NumPy on BOTH arms — keys
    non-decreasing and the row multiset preserved (exit 3 otherwise);
    (2) two full 8-way host-CPU joins, one per forced ``sort_impl``
    ("xla", "pallas_interpret"), must verify oracle-exact — so the
    timing legs can never bless a wrong kernel.  The BENCH headline
    ``value`` is the wall speedup (xla over pallas, higher is better —
    expected < 1 in interpret mode on host CPU; the chip is where the
    radix arm earns its keep), the per-arm walls land as lower-is-better
    tags, and ``sort_pass_unit_ms`` is the reduced ms/Mtuple/pass
    constant the profile fitter recovers (planner/calibrate.py
    BENCH_RADIX_SORT_METRIC).  The bounded-key leg must run FEWER passes
    and land a lower wall than the unbounded leg (the pass-skip is the
    whole point of carrying key bounds), also exit 3 on violation."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    import jax
    import jax.numpy as jnp
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.operators.hash_join import HashJoin
    from tpu_radix_join.ops.pallas.radix_sort import (num_radix_passes,
                                                      radix_pass_slots_pallas)
    from tpu_radix_join.ops.sorting import (set_default_sort_impl,
                                            sort_kv_unstable, sort_unstable)
    from tpu_radix_join.performance import Measurements

    # -- oracle sweep: both arms vs NumPy at every (lanes, bound) cell --
    rng = np.random.default_rng(13)
    n_small = 1 << 12
    for bound in (None, 1 << 16):
        hi = bound if bound is not None else 1 << 32
        keys = rng.integers(0, hi, n_small, dtype=np.uint32)
        vals = [rng.integers(0, 1 << 32, n_small, dtype=np.uint32)
                for _ in range(2)]
        for lanes in (1, 2, 3):
            ops = [jnp.asarray(keys)] + [jnp.asarray(v)
                                         for v in vals[:lanes - 1]]
            for impl in ("xla", "pallas_interpret"):
                if lanes == 1:
                    out = [sort_unstable(ops[0], impl=impl,
                                         key_bound=bound)]
                else:
                    out = list(sort_kv_unstable(*ops, impl=impl,
                                                key_bound=bound))
                got = [np.asarray(o) for o in out]
                ok = bool(np.all(got[0] == np.sort(keys)))
                # row-multiset preservation: canonicalize both sides by
                # lexicographic row order (equal keys may order their
                # value lanes differently per arm — both are unstable)
                raw = [keys] + vals[:lanes - 1]
                perm_in = np.lexsort(tuple(reversed(raw)))
                perm_out = np.lexsort(tuple(reversed(got)))
                ok = ok and all(
                    bool(np.all(r[perm_in] == g[perm_out]))
                    for r, g in zip(raw, got))
                if not ok:
                    print(f"ERROR: sort oracle mismatch (impl={impl}, "
                          f"lanes={lanes}, bound={bound})", file=sys.stderr)
                    sys.exit(3)
    print(f"note: sort oracle-exact on both arms "
          f"({n_small} keys x bounds (None, 1<<16) x 1/2/3 lanes)",
          file=sys.stderr)

    # -- end-to-end: one full join per forced sort engine --
    nodes, per_node = 8, 1 << 15
    inner = Relation(per_node * nodes, nodes, "unique", seed=31)
    outer = Relation(per_node * nodes, nodes, "unique", seed=32)
    expected = inner.expected_matches(outer)
    fallbacks = 0
    for impl in ("xla", "pallas_interpret"):
        meas = Measurements(node_id=0, num_nodes=nodes)
        eng = HashJoin(JoinConfig(num_nodes=nodes, verify="check",
                                  sort_impl=impl), measurements=meas)
        res = eng.join(inner, outer)
        if not res.ok:
            print(f"ERROR: verification failed (sort_impl={impl}): "
                  f"{res.failure}", file=sys.stderr)
            sys.exit(3)
        if expected is not None and res.matches != expected:
            print(f"ERROR: matches {res.matches} != oracle {expected} "
                  f"(sort_impl={impl})", file=sys.stderr)
            sys.exit(3)
        fallbacks = max(fallbacks, meas.counters.get("SORTFALLBACK", 0))
        print(f"note: join oracle-exact (sort_impl={impl}, "
              f"{per_node * nodes} tuples/side)", file=sys.stderr)
    set_default_sort_impl("auto")        # don't leak the forced engine

    # -- timing legs: flat 2-lane kv sort at bench scale --
    n = size
    keys = jnp.asarray(rng.integers(0, 1 << 32, n, dtype=np.uint32))
    rids = jnp.arange(n, dtype=jnp.uint32)
    bounded = jnp.asarray(rng.integers(0, 1 << 16, n, dtype=np.uint32))

    def arm(impl, k, key_bound=None):
        fn = jax.jit(lambda a, b: sort_kv_unstable(
            a, b, impl=impl, key_bound=key_bound)[0])
        return _time_amortized(fn, (k, rids), iters=2) * 1e3

    xla_wall = arm("xla", keys)
    pallas_wall = arm("pallas_interpret", keys)
    bounded_wall = arm("pallas_interpret", bounded, key_bound=1 << 16)
    passes = num_radix_passes(None)
    bounded_passes = num_radix_passes(1 << 16)
    if not (bounded_passes < passes and bounded_wall < pallas_wall):
        print(f"ERROR: bounded keys must run fewer passes at lower wall: "
              f"{bounded_passes}/{passes} passes, "
              f"{bounded_wall:.0f}/{pallas_wall:.0f} ms", file=sys.stderr)
        sys.exit(3)
    # the slot kernel alone (one digit pass; passes are cost-identical,
    # so the per-row kernel wall is one pass times the row's pass count)
    kernel_fn = jax.jit(lambda k: radix_pass_slots_pallas(
        k, shift=0, interpret=True))
    kernel_wall = _time_amortized(kernel_fn, (keys,), iters=2) * 1e3 * passes
    unit = kernel_wall / (passes * n / 1e6)
    speedup = xla_wall / max(pallas_wall, 1e-9)
    print(f"note: {n} keys kv-sorted: xla {xla_wall:.0f} ms, radix "
          f"{pallas_wall:.0f} ms/{passes}p (kernel {kernel_wall:.0f} ms), "
          f"bounded {bounded_wall:.0f} ms/{bounded_passes}p, "
          f"speedup {speedup:.2f}x, unit {unit:.4f} ms/Mtuple/pass",
          file=sys.stderr)

    result = {
        "metric": "radix_sort_speedup",
        "value": round(speedup, 3),
        "unit": "xla_over_pallas_wall",
        "vs_baseline": round(speedup, 3),
        "size": n,
        "sort_ms": round(pallas_wall, 1),
        "sort_xla_ms": round(xla_wall, 1),
        "sort_kernel_ms": round(kernel_wall, 1),
        "sort_pass_unit_ms": round(unit, 4),
        "sort_passes": passes,
        "sort_bounded_ms": round(bounded_wall, 1),
        "sort_bounded_passes": bounded_passes,
        "sortfallback": int(fallbacks),
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_serve_bench(check_baseline=None, queries=20, chaos=False):
    """``--serve-bench [N]``: the resident-service amortization bench.  N
    queries stream through ONE JoinSession on host CPU; query 0 pays mesh
    bring-up + compilation + the JHIST sizing pre-pass, every later
    same-shape query warm-starts from the session's hot capacity cache.
    Prints one BENCH JSON line whose headline ``value`` is warm
    queries/sec and whose SLO tags (slo_p99_ms, admission_rejection_rate,
    ...) gate direction-aware under tools_check_regress.py.

    ``--serve-chaos`` arms a mid-stream burst of 3 consecutive
    ``backend.dispatch`` outages: the breaker must trip, serve the next
    queries degraded on the CPU-fallback engine, recover through a
    half-open probe, and END CLOSED — with every outcome classified.
    Exit 3 on any unclassified outcome, silent wrong count, or a chaos
    run that fails to trip+recover."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    from tpu_radix_join.core.config import JoinConfig, ServiceConfig
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.robustness import faults
    from tpu_radix_join.robustness.faults import TransientFault
    from tpu_radix_join.service import UNCLASSIFIED, JoinSession, QueryRequest

    cfg = JoinConfig(num_nodes=8)
    svc = ServiceConfig(breaker_threshold=3, breaker_cooldown_s=0.05)
    meas = Measurements(node_id=0, num_nodes=8)
    session = JoinSession(cfg, svc, measurements=meas)

    burst_at = queries // 2
    inj = faults.FaultInjector(seed=7, measurements=meas)
    if chaos:
        # three consecutive primary-dispatch outages mid-stream: exactly
        # the breaker threshold, so the trip happens ON the burst
        inj.arm(faults.BACKEND_DISPATCH,
                at=tuple(range(burst_at, burst_at + 3)),
                exc=TransientFault)

    outcomes = []
    ctx = inj if chaos else contextlib.nullcontext()
    with ctx:
        for i in range(queries):
            session.submit(QueryRequest(query_id=f"q{i}",
                                        tuples_per_node=1 << 13, seed=17))
            out = session.run_next()
            outcomes.append(out)
            if chaos and out.latency_ms < 50:
                time.sleep(0.02)     # let the open-state cooldown elapse
    summary = session.summary()
    session.close()

    bad = []
    for o in outcomes:
        if o.failure_class == UNCLASSIFIED:
            bad.append(f"{o.query_id}: unclassified outcome")
        if (o.status == "ok" and o.expected is not None
                and o.matches != o.expected):
            bad.append(f"{o.query_id}: silent wrong count {o.matches} != "
                       f"{o.expected}")
    if chaos:
        if summary["breaker_trips"] < 1:
            bad.append("chaos burst did not trip the breaker")
        if summary["breaker_probes"] < 1:
            bad.append("breaker never dispatched a half-open probe")
        if summary["breaker_state"] != "closed":
            bad.append(f"breaker ended {summary['breaker_state']}, "
                       f"not closed")
        if summary["degraded_queries"] < 1:
            bad.append("no query served degraded while open")
    if bad:
        for b in bad:
            print(f"ERROR: {b}", file=sys.stderr)
        return 3

    cold_ms = outcomes[0].latency_ms
    warm = sorted(o.latency_ms for o in outcomes if o.warm)
    warm_p50 = warm[len(warm) // 2] if warm else float("nan")
    warm_qps = (len(warm) / (sum(warm) / 1e3)) if warm else 0.0
    for o in outcomes:
        print(f"note: {o.query_id} {o.status}/{o.failure_class} "
              f"{o.latency_ms:.1f} ms engine={o.engine}"
              f"{' warm' if o.warm else ''} breaker={o.breaker_state}",
              file=sys.stderr)
    result = {
        "metric": "resident_join_service",
        "value": round(warm_qps, 3),
        "unit": "queries/sec",
        "queries": queries,
        "cold_latency_ms": round(cold_ms, 3),
        "warm_latency_p50_ms": round(warm_p50, 3),
        "warm_speedup": round(cold_ms / warm_p50, 2) if warm else 0.0,
        "warm_queries": summary["warm_queries"],
        "degraded_queries": summary["degraded_queries"],
        "breaker_trips": summary["breaker_trips"],
        "breaker_probes": summary["breaker_probes"],
        "admission_rejection_rate": summary["admission_rejection_rate"],
        "deadline_miss_rate": summary["deadline_miss_rate"],
        "degraded_rate": summary["degraded_rate"],
        "slo_p50_ms": summary.get("slo_p50_ms"),
        "slo_p95_ms": summary.get("slo_p95_ms"),
        "slo_p99_ms": summary.get("slo_p99_ms"),
        "chaos": chaos,
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_serve_throughput_bench(check_baseline=None):
    """``--serve-throughput-bench``: the serving fast-path A/B for the
    three gated tiers (ROADMAP serving throughput: result cache,
    micro-batching, delta-merge), all on host CPU.

    Four legs, each oracle-exact or exit 3:

      * **cache** — one session with the fingerprint result cache on: a
        timed cold execution vs the timed repeat of the SAME content.
        The repeat must come back ``served_by=cache_hit`` with the cold
        answer and >= 10x faster (the tier exists to skip admission and
        execution entirely, so anything less means it executed).
      * **batch** — per batch size Q in {2, 4, 8}: a serial drain of Q
        co-signature queries vs the SAME queries drained through ONE
        fused device program (``served_by=batched``).  Warm pass first
        so both arms price steady-state serving, not compilation; the
        fused arm must beat serial by >= 1.5x at Q=4.
      * **delta** — per Δ/N in {1/16, 1/64, 1/256}: a resident session
        absorbing three deltas O(N+Δ) (``served_by=delta_merge``, the
        unchanged-outer incremental probe) vs the budget-0 posture
        re-sorting and re-probing from scratch every query; >= 2x at
        Δ/N = 1/64.
      * **fleet chaos** — a 2-worker fleet coalescing a 4-query
        co-batchable group through one worker, SIGKILLed mid-batch
        (``fleet.worker_kill``): every query must still end oracle-exact
        through journaled failover and the drain audit must report
        ``unacked == 0`` and ``double_exec == 0``.

    The statusz leg polls a live ``/statusz`` 5 times plus ``/healthz``
    against the cache/batch sections while the session serves — the
    introspection plane must answer every poll mid-serving.

    The BENCH headline ``value`` is the Q=4 fused-over-serial speedup;
    cache_speedup / delta_speedup / batch_fuse_ratio and the six serving
    counters ride as tags, direction-gated under tools_check_regress.py
    (double_exec pins to zero)."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    import statistics
    import tempfile
    import urllib.request

    from tpu_radix_join.core.config import JoinConfig, ServiceConfig
    from tpu_radix_join.observability.statusz import StatuszServer
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.performance.measurements import (BATCHN, BATCHQ,
                                                         DELTAMERGE, FAILOVER,
                                                         RCHIT, RCMISS,
                                                         RESBYTES)
    from tpu_radix_join.robustness import faults
    from tpu_radix_join.service import JoinSession, QueryRequest
    from tpu_radix_join.service.fleet import FleetSupervisor

    cfg = JoinConfig(num_nodes=8)
    bad = []

    def exact(out, leg):
        if not (out is not None and out.status == "ok"
                and out.expected is not None
                and out.matches == out.expected):
            bad.append(
                f"{leg}: {getattr(out, 'query_id', None)} not oracle-exact "
                f"({getattr(out, 'status', 'missing')} "
                f"matches={getattr(out, 'matches', None)} "
                f"expected={getattr(out, 'expected', None)} "
                f"{getattr(out, 'detail', '')})")
            return False
        return True

    # ---- leg 1: result cache + the statusz/healthz liveness poll
    svc = ServiceConfig(result_cache_max=8, batch_window_ms=25.0,
                        batch_max_queries=8, default_deadline_s=300.0)
    meas = Measurements(node_id=0, num_nodes=8)
    session = JoinSession(cfg, svc, measurements=meas)
    statusz = StatuszServer(port=0, sections={
        "cache": lambda: session.result_cache.stats(),
        "batch": lambda: {"fused_batches": session.batches_fused,
                          "fused_queries": session.batch_queries_fused},
    })
    statusz.start()
    url = f"http://127.0.0.1:{statusz.port}"
    polls = 0
    try:
        session.submit(QueryRequest(query_id="warm",
                                    tuples_per_node=1 << 13, seed=3))
        session.run_next()          # engine + compile warm-up, seed 3
        t0 = time.perf_counter()
        session.submit(QueryRequest(query_id="cold",
                                    tuples_per_node=1 << 13, seed=5))
        cold = session.run_next()
        cold_ms = (time.perf_counter() - t0) * 1e3
        hit = session.try_cache(QueryRequest(query_id="hit",
                                             tuples_per_node=1 << 13,
                                             seed=5))
        # 5-poll liveness against the serving session: every poll must
        # answer with the cache/batch sections present, plus /healthz
        for _ in range(5):
            with urllib.request.urlopen(f"{url}/statusz",
                                        timeout=5) as resp:
                page = json.loads(resp.read())
            if "cache" not in page.get("sections", page):
                bad.append("statusz poll lost the cache section")
            polls += 1
        with urllib.request.urlopen(f"{url}/healthz", timeout=5) as resp:
            if resp.status != 200:
                bad.append(f"/healthz answered {resp.status}")
        cache_stats = session.result_cache.stats()
    finally:
        statusz.stop()
        session.close()
    exact(cold, "cache-cold")
    if hit is None or hit.served_by != "cache_hit":
        bad.append(f"repeat content did not cache-serve "
                   f"(served_by={getattr(hit, 'served_by', None)})")
        cache_speedup = 0.0
        hit_ms = float("nan")
    else:
        exact(hit, "cache-hit")
        if hit.matches != cold.matches:
            bad.append(f"cache hit answer drifted: {hit.matches} != "
                       f"{cold.matches}")
        hit_ms = hit.latency_ms
        cache_speedup = cold_ms / max(hit_ms, 1e-9)
        if cache_speedup < 10.0:
            bad.append(f"cache hit only {cache_speedup:.1f}x over cold "
                       f"({hit_ms:.3f} vs {cold_ms:.1f} ms); gate is 10x")
    if polls < 5:
        bad.append(f"only {polls}/5 statusz polls answered")

    # ---- leg 2: micro-batch fuse A/B at Q = 2, 4, 8
    def batch_arm(q, fuse, tag):
        svc = ServiceConfig(batch_window_ms=50.0 if fuse else 0.0,
                            batch_max_queries=8, default_deadline_s=300.0)
        m2 = Measurements(node_id=0, num_nodes=8)
        s2 = JoinSession(cfg, svc, measurements=m2)
        try:
            walls = []
            outs = []
            for rnd in ("w", "t"):          # warm pass, then timed pass
                for i in range(q):
                    s2.submit(QueryRequest(query_id=f"{tag}{rnd}{i}",
                                           tuples_per_node=1 << 10,
                                           seed=23))
                t0 = time.perf_counter()
                outs = s2.drain(batched=fuse)
                walls.append((time.perf_counter() - t0) * 1e3)
            for o in outs:
                exact(o, f"batch-q{q}-{'fused' if fuse else 'serial'}")
                want = "batched" if fuse else "execute"
                if o.served_by != want:
                    bad.append(f"{o.query_id}: served_by={o.served_by}, "
                               f"want {want}")
            return walls[-1], m2
        finally:
            s2.close()

    batch_speedups = {}
    batchn = batchq = 0
    for q in (2, 4, 8):
        serial_ms, _ = batch_arm(q, fuse=False, tag=f"s{q}")
        fused_ms, mf = batch_arm(q, fuse=True, tag=f"f{q}")
        batchn += int(mf.counters.get(BATCHN, 0))
        batchq += int(mf.counters.get(BATCHQ, 0))
        batch_speedups[q] = serial_ms / max(fused_ms, 1e-9)
        print(f"note: batch q={q}: serial {serial_ms:.1f} ms vs fused "
              f"{fused_ms:.1f} ms -> {batch_speedups[q]:.2f}x",
              file=sys.stderr)
    if batch_speedups[4] < 1.5:
        bad.append(f"fused batch of 4 only {batch_speedups[4]:.2f}x over "
                   f"serial; gate is 1.5x")
    fuse_ratio = batchq / batchn if batchn else 0.0

    # ---- leg 3: delta-merge A/B at Δ/N = 1/16, 1/64, 1/256
    def delta_arm(budget, ratio, tag):
        svc = ServiceConfig(resident_budget_bytes=budget,
                            default_deadline_s=300.0)
        m3 = Measurements(node_id=0, num_nodes=8)
        s3 = JoinSession(cfg, svc, measurements=m3)
        nt = 1 << 14
        try:
            lats, outs = [], []
            for i in range(4):
                s3.submit(QueryRequest(
                    query_id=f"{tag}{i}", tuples_per_node=nt,
                    delta_tuples_per_node=max(1, nt // ratio), seed=11))
                out = s3.run_next()
                outs.append(out)
                lats.append(out.latency_ms)
            for o in outs:
                exact(o, f"delta-1/{ratio}-"
                         f"{'resident' if budget else 'full'}")
            if budget:
                hot = [o.served_by for o in outs[1:]]
                if hot != ["delta_merge"] * 3:
                    bad.append(f"resident arm 1/{ratio} not on the delta "
                               f"path: {hot}")
            # query 0 is the cold seed in BOTH arms; steady state is q1..3
            return statistics.mean(lats[1:]), m3
        finally:
            s3.close()

    delta_speedups = {}
    deltamerge = resbytes = 0
    for ratio in (16, 64, 256):
        # warm pass compiles the per-shape programs (process-global
        # lru_cache in ops/merge_delta.py), so the timed pass prices
        # serving, not tracing
        delta_arm(1 << 27, ratio, f"dwr{ratio}_")
        delta_arm(0, ratio, f"dwf{ratio}_")
        hot_ms, mr = delta_arm(1 << 27, ratio, f"dr{ratio}_")
        cold_ms_d, _ = delta_arm(0, ratio, f"df{ratio}_")
        deltamerge += int(mr.counters.get(DELTAMERGE, 0))
        resbytes = max(resbytes, int(mr.counters.get(RESBYTES, 0)))
        delta_speedups[ratio] = cold_ms_d / max(hot_ms, 1e-9)
        print(f"note: delta 1/{ratio}: resident {hot_ms:.1f} ms vs full "
              f"re-sort {cold_ms_d:.1f} ms -> "
              f"{delta_speedups[ratio]:.2f}x", file=sys.stderr)
    if delta_speedups[64] < 2.0:
        bad.append(f"delta merge at 1/64 only {delta_speedups[64]:.2f}x "
                   f"over the full re-sort; gate is 2x")

    # ---- leg 4: mid-batch worker kill must not break exactly-once
    tmp = tempfile.mkdtemp(prefix="serve_tp_bench_")
    tpn_c = 1 << 10
    worker_args = ["--nodes", "1", "--verify", "check",
                   "--batch-window-ms", "25", "--batch-max", "8"]
    mF = Measurements()
    sup = FleetSupervisor(2, worker_args, os.path.join(tmp, "chaos"),
                          measurements=mF, lease_s=1.0,
                          batch_window_ms=25.0)
    double_exec = -1
    try:
        sup.start()
        warm = sup.dispatch({"query_id": "cw", "tenant": "t0",
                             "tuples_per_node": tpn_c, "seed": 7})
        if not (warm.get("status") == "ok"
                and warm.get("matches") == tpn_c):
            bad.append(f"fleet warm-up not oracle-exact: {warm.get('status')} "
                       f"matches={warm.get('matches')}")
        group = [{"query_id": f"c{i}", "tenant": "t0",
                  "tuples_per_node": tpn_c, "seed": 7 + i}
                 for i in range(4)]
        # the kill site fires per written query (1-based): the routed
        # worker dies right after the first write, mid-group — the
        # unanswered remainder must fail over under its journaled
        # fingerprints
        with faults.FaultInjector(seed=13, measurements=mF).arm(
                faults.FLEET_WORKER_KILL, at=1):
            outs = sup.dispatch_batch(group)
        for o in outs:
            if not (o.get("status") == "ok"
                    and o.get("matches") == tpn_c):
                bad.append(f"mid-batch kill lost {o.get('query_id')}: "
                           f"{o.get('status')} "
                           f"matches={o.get('matches')} != {tpn_c} "
                           f"({o.get('detail')})")
        report = sup.drain()
        double_exec = report["double_exec"]
        if report["unacked"] or report["double_exec"]:
            bad.append(f"mid-batch kill broke exactly-once at drain: "
                       f"{report}")
        if int(mF.counters.get(FAILOVER, 0)) < 1:
            bad.append("mid-batch kill never failed over — the chaos "
                       "site did not fire (armed at write 1)")
        print(f"note: mid-batch kill: 4/4 exact through failover, "
              f"restarts={sup.restarts}, drain={report}", file=sys.stderr)
    finally:
        sup.close()

    if bad:
        for b in bad:
            print(f"ERROR: {b}", file=sys.stderr)
        return 3

    print(f"note: cache {cache_speedup:.0f}x (cold {cold_ms:.1f} ms -> "
          f"hit {hit_ms:.3f} ms), batch {batch_speedups[4]:.2f}x at q=4, "
          f"delta {delta_speedups[64]:.2f}x at 1/64", file=sys.stderr)
    result = {
        "metric": "serve_fastpath_speedup",
        "value": round(batch_speedups[4], 3),
        "unit": "serial_over_fused_wall_q4",
        "cache_cold_latency_ms": round(cold_ms, 3),
        "cache_hit_latency_ms": round(hit_ms, 4),
        "cache_speedup": round(cache_speedup, 1),
        "cache_hit_rate": cache_stats["hit_rate"],
        "batch_speedup_2": round(batch_speedups[2], 3),
        "batch_speedup_4": round(batch_speedups[4], 3),
        "batch_speedup_8": round(batch_speedups[8], 3),
        "batch_fuse_ratio": round(fuse_ratio, 3),
        "delta_speedup_16": round(delta_speedups[16], 3),
        "delta_speedup_64": round(delta_speedups[64], 3),
        "delta_speedup_256": round(delta_speedups[256], 3),
        "delta_speedup": round(delta_speedups[64], 3),
        "rchit": int(meas.counters.get(RCHIT, 0)),
        "rcmiss": int(meas.counters.get(RCMISS, 0)),
        "batchn": batchn,
        "batchq": batchq,
        "deltamerge": deltamerge,
        "resbytes": resbytes,
        "statusz_polls": polls,
        "double_exec": double_exec,
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_critpath_bench(check_baseline=None, size=1 << 20, iters=5):
    """``--critpath-bench``: instrumentation-overhead A/B for the
    critical-path attribution plane (observability/critpath.py +
    statusz.py).

    Two arms of the same 1M x 1M 8-way host-CPU join: the BARE arm runs
    with the registry alone (the pre-observability posture); the
    INSTRUMENTED arm attaches the span tracer, keeps a live ``/statusz``
    endpoint up and polls it once per join (the operator's heartbeat
    query), and reconstructs the critical path after every join — the
    full cost of the introspection plane under load.  Per-arm walls are
    per-iteration medians, so one scheduler hiccup cannot fake a
    regression.  The headline ``value`` is instrumented throughput;
    ``critpath_overhead_pct`` and the path's ``wait_fraction`` gate
    lower-is-better under tools_check_regress.py.  Exit 3 when either
    arm misses the oracle or the overhead exceeds the 1%% acceptance
    bar."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    import urllib.request

    import jax.numpy as jnp
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.observability.critpath import (
        critical_path_from_tracer)
    from tpu_radix_join.observability.statusz import (StatuszServer,
                                                      measurements_sections)
    from tpu_radix_join.operators.hash_join import HashJoin
    from tpu_radix_join.performance import Measurements

    nodes, n = 8, size
    cfg = JoinConfig(num_nodes=nodes)
    rng = np.random.default_rng(29)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    rid = np.arange(n, dtype=np.uint32)
    r = TupleBatch(key=jnp.asarray(rk), rid=jnp.asarray(rid))
    s = TupleBatch(key=jnp.asarray(sk), rid=jnp.asarray(rid))

    def median(vals):
        vs = sorted(vals)
        return vs[len(vs) // 2]

    def bare_arm():
        meas = Measurements(node_id=0, num_nodes=nodes)
        eng = HashJoin(cfg, measurements=meas)
        res = eng.join_arrays(r, s)              # compile warm-up
        walls = []
        for _ in range(iters):
            t0 = time.perf_counter()
            res = eng.join_arrays(r, s)
            walls.append((time.perf_counter() - t0) * 1e3)
        return res, median(walls)

    def instrumented_arm():
        meas = Measurements(node_id=0, num_nodes=nodes)
        meas.attach_tracer(nodes=nodes)
        eng = HashJoin(cfg, measurements=meas)
        statusz = StatuszServer(port=0,
                                sections=measurements_sections(meas))
        statusz.start()
        url = f"http://127.0.0.1:{statusz.port}/statusz"
        cp = None
        try:
            res = eng.join_arrays(r, s)          # compile warm-up
            walls = []
            for _ in range(iters):
                t0 = time.perf_counter()
                res = eng.join_arrays(r, s)
                with urllib.request.urlopen(url, timeout=5) as resp:
                    json.loads(resp.read())
                cp = critical_path_from_tracer(meas.tracer)
                walls.append((time.perf_counter() - t0) * 1e3)
            polls = statusz.requests_served
        finally:
            statusz.stop()
        return res, median(walls), cp, polls

    res_bare, bare_ms = bare_arm()
    res_inst, inst_ms, cp, polls = instrumented_arm()
    for arm, res in (("bare", res_bare), ("instrumented", res_inst)):
        if not (res.ok and res.matches == n):
            print(f"ERROR: {arm} arm missed the oracle: {res.matches} "
                  f"!= {n}", file=sys.stderr)
            return 3
    if cp is None or cp.get("error"):
        print(f"ERROR: no critical path reconstructed: "
              f"{(cp or {}).get('error')}", file=sys.stderr)
        return 3
    overhead_pct = 100.0 * (inst_ms - bare_ms) / max(bare_ms, 1e-9)
    mtps = (2 * n / 1e6) / (inst_ms / 1e3)
    print(f"note: {n}x{n} join: bare {bare_ms:.1f} ms vs instrumented "
          f"{inst_ms:.1f} ms (tracer + {polls} statusz polls + per-join "
          f"critpath) -> overhead {overhead_pct:+.2f}%, path bound by "
          f"rank {cp['bounding_rank']}, wait fraction "
          f"{cp['wait_fraction']:.3f}", file=sys.stderr)
    result = {
        "metric": "critpath_overhead",
        "value": round(mtps, 3),
        "unit": "Mtuples/sec_instrumented",
        "size": n,
        "critpath_overhead_pct": round(max(0.0, overhead_pct), 3),
        "wait_fraction": cp["wait_fraction"],
        "bare_wall_ms": round(bare_ms, 2),
        "instrumented_wall_ms": round(inst_ms, 2),
        "statusz_polls": polls,
        "critpath_path_ms": cp["path_ms"],
        "critpath_barriers": len(cp.get("barriers", [])),
    }
    print(json.dumps(result))
    _ledger_append(result)
    if overhead_pct > 1.0:
        print(f"ERROR: introspection overhead {overhead_pct:.2f}% exceeds "
              "the 1% acceptance bar", file=sys.stderr)
        return 3
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_recovery_bench(check_baseline=None, size=1 << 18):
    """``--recovery-bench``: the elastic-recovery A/B — kill-1-of-8
    partition-level recovery versus the cold full restart it replaces.

    Both arms run an 8-way host-CPU mesh at ``size`` tuples per side with
    the oracle-friendly chaos inputs (R a permutation of 1..n, S uniform,
    true count exactly n).  The **restart arm** times a full warm join —
    what a non-elastic job pays after ANY rank death.  The **recovery
    arm** models the kill: a partition manifest holds the true counts of
    every partition the dead rank did NOT own (realized pre-death), the
    ``membership.rank_death`` site fires mid-join, and the elastic engine
    resumes the manifest + recomputes only the dead rank's partitions
    host-side.  Both arms are compile-warmed before timing.

    Exit 3 unless the recovered count is oracle-exact AND the recompute
    stayed partition-granular (``RECOVERN`` strictly below the partition
    count).  The BENCH headline ``value`` is the wall ratio (cold restart
    over recovery, higher is better); ``recover_ms``/``cold_restart_ms``/
    ``recovern``/``ranklost``/``mepoch`` gate lower-is-better under
    tools_check_regress.py."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    import tempfile

    import jax.numpy as jnp
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.operators.hash_join import HashJoin
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.performance.measurements import (MEPOCH, RANKLOST,
                                                         RECOVERN)
    from tpu_radix_join.robustness import faults
    from tpu_radix_join.robustness.checkpoint import PartitionManifest

    nodes, n = 8, size
    cfg = JoinConfig(num_nodes=nodes, network_fanout_bits=4, verify="check")
    num_p = cfg.network_partition_count
    dead = nodes - 1                       # _rank_death's simulated victim
    rng = np.random.default_rng(23)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    rid = np.arange(n, dtype=np.uint32)
    r = TupleBatch(key=jnp.asarray(rk), rid=jnp.asarray(rid))
    s = TupleBatch(key=jnp.asarray(sk), rid=jnp.asarray(rid))
    # every S key matches exactly one R key, so a partition's true count
    # is its S-key population — what the manifest would hold post-realize
    true = np.bincount(sk & (num_p - 1), minlength=num_p)

    # ---- restart arm: the full warm join a non-elastic job re-pays
    eng = HashJoin(cfg, measurements=Measurements(num_nodes=nodes))
    res = eng.join_arrays(r, s)            # compile warm-up
    if not (res.ok and res.matches == n):
        print(f"ERROR: baseline join missed the oracle: {res.matches} "
              f"!= {n}", file=sys.stderr)
        return 3
    t0 = time.perf_counter()
    eng.join_arrays(r, s)
    cold_ms = (time.perf_counter() - t0) * 1e3

    # ---- recovery arm: manifest resumes all but the dead rank's share
    tmp = tempfile.mkdtemp(prefix="recovery_bench_")
    eng.elastic = True

    def one_recovery(tag):
        man = PartitionManifest(os.path.join(tmp, f"m_{tag}.manifest"),
                                fingerprint={"bench": "recovery"})
        man.mark_many({p: int(true[p]) for p in range(num_p)
                       if p % nodes != dead}, owner_of=lambda p: p % nodes)
        m = Measurements(num_nodes=nodes)
        eng.measurements = m
        eng.partition_manifest = man
        try:
            with faults.FaultInjector(seed=5, measurements=m).arm(
                    faults.RANK_DEATH, at=2):
                t0 = time.perf_counter()
                out = eng.join_arrays(r, s)
                wall_ms = (time.perf_counter() - t0) * 1e3
        finally:
            eng.partition_manifest = None
        return out, wall_ms, m

    one_recovery("warm")                   # compile-warm the masked grids
    out, recover_ms, m = one_recovery("timed")
    recovern = int(m.counters.get(RECOVERN, 0))
    if not (out.ok and out.matches == n):
        print(f"ERROR: recovered join missed the oracle: "
              f"{out.matches} != {n}", file=sys.stderr)
        return 3
    if not 0 < recovern < num_p:
        print(f"ERROR: recompute was not partition-granular: RECOVERN="
              f"{recovern} of {num_p} partitions", file=sys.stderr)
        return 3
    resumed = len(out.diagnostics.get("resumed_partitions") or [])
    speedup = cold_ms / max(recover_ms, 1e-9)
    print(f"note: kill-1-of-{nodes}: recovery {recover_ms:.0f} ms "
          f"({recovern}/{num_p} partitions recomputed, {resumed} resumed) "
          f"vs cold restart {cold_ms:.0f} ms -> {speedup:.2f}x",
          file=sys.stderr)

    result = {
        "metric": "elastic_recovery_speedup",
        "value": round(speedup, 3),
        "unit": "cold_restart_over_recovery_wall",
        "size": n,
        "num_partitions": num_p,
        "recover_ms": round(recover_ms, 1),
        "cold_restart_ms": round(cold_ms, 1),
        "recovern": recovern,
        "resumed_partitions": resumed,
        "ranklost": int(m.counters.get(RANKLOST, 0)),
        "mepoch": int(m.counters.get(MEPOCH, 0)),
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_recovery_straggle_bench(check_baseline=None, factor=4.0,
                                 size=1 << 17):
    """``--recovery-bench --straggle f``: the hedged-vs-unhedged tail A/B.

    One rank of the 8-way host mesh stalls for ``f x straggle_unit_s``
    mid-join (the ``compute.straggle`` site).  The **unhedged arm** eats
    the stall in full — the whole join stretches by the slowest rank,
    the reference's RMA-window failure mode.  The **hedged arm** lets the
    relative-progress detector (robustness/straggler.py) flag the victim
    off manifest progress and speculatively recomputes its unfinished
    stripe through the manifest fence — first writer wins, so even a
    late-finishing original could not double-count.  The manifest
    pre-realizes every partition OUTSIDE the victim's stripe (the counts
    a healthy rank would have posted pre-stall), so the hedge recompute
    must stay partition-granular — a hedge that recomputes everything is
    a veiled restart and exits 3 exactly like the shrink bench's gate.

    Exit 3 unless both arms are oracle-exact, HEDGEWIN >= 1, the hedge
    stayed partition-granular, the manifest audit sums to the oracle,
    and the hedged tail beats the unhedged tail.  ``hedged_ms``/
    ``unhedged_ms``/``specwaste`` gate lower-is-better, the headline
    ``value`` (unhedged over hedged wall) higher-is-better."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    import tempfile

    import jax.numpy as jnp
    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.data.tuples import TupleBatch
    from tpu_radix_join.operators.hash_join import HashJoin
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.performance.measurements import (HEDGED, HEDGEWIN,
                                                         RECOVERN, SPECWASTE)
    from tpu_radix_join.robustness import faults
    from tpu_radix_join.robustness.checkpoint import PartitionManifest
    from tpu_radix_join.robustness.membership import (LeaseBoard,
                                                      MembershipView)

    nodes, n = 8, size
    cfg = JoinConfig(num_nodes=nodes, network_fanout_bits=5, verify="check")
    num_p = cfg.network_partition_count
    victim = nodes - 1                 # _compute_straggle's simulated victim
    rng = np.random.default_rng(29)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    rid = np.arange(n, dtype=np.uint32)
    r = TupleBatch(key=jnp.asarray(rk), rid=jnp.asarray(rid))
    s = TupleBatch(key=jnp.asarray(sk), rid=jnp.asarray(rid))
    true = np.bincount(sk & (num_p - 1), minlength=num_p)

    tmp = tempfile.mkdtemp(prefix="straggle_bench_")
    eng = HashJoin(cfg, measurements=Measurements(num_nodes=nodes))
    eng.elastic = True
    eng.straggle_factor = float(factor)
    eng.straggle_unit_s = 0.25         # the stall the unhedged arm eats

    def one_arm(tag, hedge):
        man = PartitionManifest(os.path.join(tmp, f"m_{tag}.manifest"),
                                fingerprint={"bench": "straggle"})
        man.mark_many({p: int(true[p]) for p in range(num_p)
                       if p % nodes != victim}, owner_of=lambda p: p % nodes)
        m = Measurements(num_nodes=nodes)
        board = LeaseBoard(os.path.join(tmp, f"leases_{tag}"), rank=0,
                           num_ranks=1, lease_s=300.0, measurements=m)
        membership = MembershipView(board, measurements=m)
        board.heartbeat(0)
        eng.measurements = m
        eng.partition_manifest = man
        eng.membership = membership
        eng.hedge = hedge
        try:
            with faults.FaultInjector(seed=7, measurements=m).arm(
                    faults.COMPUTE_STRAGGLE, at=1):
                t0 = time.perf_counter()
                out = eng.join_arrays(r, s)
                wall_ms = (time.perf_counter() - t0) * 1e3
        finally:
            eng.partition_manifest = None
            eng.membership = None
            eng.hedge = "off"
        return out, wall_ms, m, man

    one_arm("warm_off", "off")         # compile-warm the plain join
    one_arm("warm_on", "on")           # compile-warm the masked grids
    out_u, unhedged_ms, _, _ = one_arm("timed_off", "off")
    out_h, hedged_ms, mh, man_h = one_arm("timed_on", "on")
    recovern = int(mh.counters.get(RECOVERN, 0))
    hedgewin = int(mh.counters.get(HEDGEWIN, 0))
    aud = man_h.audit()
    for tag, out in (("unhedged", out_u), ("hedged", out_h)):
        if not (out.ok and out.matches == n):
            print(f"ERROR: {tag} arm missed the oracle: {out.matches} "
                  f"!= {n}", file=sys.stderr)
            return 3
    if int(mh.counters.get(HEDGED, 0)) < 1 or hedgewin < 1:
        print(f"ERROR: the hedge never engaged or never won a fence: "
              f"HEDGED={int(mh.counters.get(HEDGED, 0))} "
              f"HEDGEWIN={hedgewin}", file=sys.stderr)
        return 3
    if not 0 < recovern < num_p:
        print(f"ERROR: hedge recompute was not partition-granular (a "
              f"veiled restart): RECOVERN={recovern} of {num_p} "
              f"partitions", file=sys.stderr)
        return 3
    if aud["total"] != n:
        print(f"ERROR: manifest audit does not sum to the oracle: "
              f"{aud['total']} != {n} "
              f"(fenced_duplicates={aud['fenced_duplicates']})",
              file=sys.stderr)
        return 3
    speedup = unhedged_ms / max(hedged_ms, 1e-9)
    if speedup <= 1.0:
        print(f"ERROR: hedged arm was not faster: {hedged_ms:.0f} ms "
              f"hedged vs {unhedged_ms:.0f} ms unhedged", file=sys.stderr)
        return 3
    print(f"note: straggle x{factor}: hedged {hedged_ms:.0f} ms "
          f"({recovern}/{num_p} partitions speculated, {hedgewin} fence "
          f"wins) vs unhedged {unhedged_ms:.0f} ms -> {speedup:.2f}x",
          file=sys.stderr)

    result = {
        "metric": "straggler_hedge_tail_speedup",
        "value": round(speedup, 3),
        "unit": "unhedged_tail_over_hedged_tail",
        "size": n,
        "num_partitions": num_p,
        "straggle_factor": float(factor),
        "hedged_ms": round(hedged_ms, 1),
        "unhedged_ms": round(unhedged_ms, 1),
        "hedgewin": hedgewin,
        "specwaste": int(mh.counters.get(SPECWASTE, 0)),
        "recovern": recovern,
        "manifest_total": int(aud["total"]),
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_recovery_grow_bench(check_baseline=None, size=1 << 19):
    """``--recovery-bench --grow``: mid-run admission speedup vs fixed
    survivors.

    Scenario: a join is mid-flight with 14 of the 32 partitions realized
    in the manifest when a ninth process writes a ``joining`` lease; the
    board admits it with a fenced epoch bump (the REAL admission path —
    MembershipView.check over a lease dir, RANKJOIN ticks) and the
    recovery plan re-expands `load_aware_assignment` over the enlarged
    membership.  Both arms recompute the same 18 unfinished partitions
    through `execute_recovery(only_rank=...)` per survivor; the reported
    wall is the **critical path** — the slowest single survivor's share,
    which is what decides when a data-parallel epoch completes.  The
    fixed arm spreads 18 partitions over 8 survivors (max share 3), the
    grown arm over 9 (max share 2).

    Exit 3 unless the merged count is oracle-exact on both arms, the
    recompute stayed partition-granular (the veiled-restart refusal the
    shrink bench pioneered: resumed > 0 and recomputed < num_p), and the
    grown critical path beats the fixed one.  ``grown_ms``/``fixed_ms``
    gate lower-is-better; ``value`` (fixed over grown) higher-is-better."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    import tempfile

    from tpu_radix_join.core.config import JoinConfig
    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.performance.measurements import RANKJOIN, RECOVERN
    from tpu_radix_join.robustness.checkpoint import PartitionManifest
    from tpu_radix_join.robustness.membership import (LeaseBoard,
                                                      MembershipView)
    from tpu_radix_join.robustness.recovery import (execute_recovery,
                                                    partition_weights,
                                                    plan_recovery)

    nodes, n = 8, size
    cfg = JoinConfig(num_nodes=nodes, network_fanout_bits=5, verify="check")
    num_p = cfg.network_partition_count
    rng = np.random.default_rng(31)
    rk = (rng.permutation(n) + 1).astype(np.uint32)
    sk = rng.integers(1, n + 1, size=n).astype(np.uint32)
    true = np.bincount(sk & (num_p - 1), minlength=num_p)
    realized = list(range(14))             # partitions done pre-admission
    weights = partition_weights(rk, sk, num_p)

    # -- the admission itself rides the real lease protocol: incumbents
    # hold member leases, the newcomer writes a joining lease, one
    # check() batch admits it with the fenced epoch bump
    tmp = tempfile.mkdtemp(prefix="grow_bench_")
    m = Measurements(num_nodes=nodes)
    lease_dir = os.path.join(tmp, "leases")
    for incumbent in range(nodes):
        LeaseBoard(lease_dir, rank=incumbent, num_ranks=nodes,
                   lease_s=300.0).heartbeat(0)
    board = LeaseBoard(lease_dir, rank=0, num_ranks=nodes, lease_s=300.0,
                       measurements=m)
    joiner_rank = LeaseBoard.next_rank(lease_dir, floor=nodes)
    LeaseBoard(lease_dir, rank=joiner_rank, num_ranks=nodes,
               lease_s=300.0).heartbeat(0, status="joining")
    mv = MembershipView(board, measurements=m)
    mv.check()
    if joiner_rank not in mv.joined or mv.epoch != 1:
        print(f"ERROR: admission did not land: joined={sorted(mv.joined)} "
              f"epoch={mv.epoch}", file=sys.stderr)
        return 3
    rankjoin = int(m.counters.get(RANKJOIN, 0))

    def one_arm(tag, joined_ranks):
        man = PartitionManifest(os.path.join(tmp, f"m_{tag}.manifest"),
                                fingerprint={"bench": "grow"})
        man.mark_many({p: int(true[p]) for p in realized},
                      owner_of=lambda p: p % nodes)
        plan = plan_recovery(num_nodes=nodes, num_partitions=num_p,
                             lost_ranks=[], epoch=mv.epoch, manifest=man,
                             weights=weights, joined_ranks=joined_ranks)
        am = Measurements(num_nodes=nodes)
        critical_ms, matches = 0.0, 0
        for survivor in plan.survivors:
            t0 = time.perf_counter()
            matches, _ = execute_recovery(plan, rk, sk,
                                          only_rank={survivor},
                                          manifest=man, measurements=am)
            critical_ms = max(critical_ms,
                              (time.perf_counter() - t0) * 1e3)
        return plan, critical_ms, matches, int(
            am.counters.get(RECOVERN, 0)), man

    one_arm("warm", ())                    # compile-warm the masked grids
    plan_f, fixed_ms, matches_f, recovern_f, _ = one_arm("fixed", ())
    plan_g, grown_ms, matches_g, recovern_g, man_g = one_arm(
        "grown", sorted(mv.joined))
    for tag, matches in (("fixed", matches_f), ("grown", matches_g)):
        if matches != n:
            print(f"ERROR: {tag} arm missed the oracle: {matches} != {n}",
                  file=sys.stderr)
            return 3
    for tag, recovern in (("fixed", recovern_f), ("grown", recovern_g)):
        if not (len(realized) > 0 and 0 < recovern < num_p):
            print(f"ERROR: {tag} arm recompute was not partition-granular "
                  f"(a veiled restart): RECOVERN={recovern} of {num_p} "
                  f"partitions, {len(realized)} resumed", file=sys.stderr)
            return 3
    if joiner_rank not in set(plan_g.reassignment.values()):
        print(f"ERROR: the grown plan never assigned the newcomer "
              f"(rank {joiner_rank}) a partition: "
              f"{plan_g.reassignment}", file=sys.stderr)
        return 3
    speedup = fixed_ms / max(grown_ms, 1e-9)
    if speedup <= 1.0:
        print(f"ERROR: grown arm was not faster: {grown_ms:.0f} ms grown "
              f"vs {fixed_ms:.0f} ms fixed", file=sys.stderr)
        return 3
    print(f"note: join-mid-run: grown critical path {grown_ms:.0f} ms "
          f"({len(plan_g.survivors)} survivors) vs fixed {fixed_ms:.0f} ms "
          f"({len(plan_f.survivors)}) -> {speedup:.2f}x",
          file=sys.stderr)

    result = {
        "metric": "elastic_grow_speedup",
        "value": round(speedup, 3),
        "unit": "fixed_critical_path_over_grown",
        "size": n,
        "num_partitions": num_p,
        "grown_ms": round(grown_ms, 1),
        "fixed_ms": round(fixed_ms, 1),
        "recovern": recovern_g,
        "resumed_partitions": len(realized),
        "rankjoin": rankjoin,
        "survivors_fixed": len(plan_f.survivors),
        "survivors_grown": len(plan_g.survivors),
        "manifest_total": int(man_g.audit()["total"]),
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def _run_fleet_bench(check_baseline=None, workers=4, tpn=1 << 10):
    """``--fleet-bench``: the crash-only fleet failover A/B — kill-1-of-4
    mid-query failover versus the cold supervisor restart it replaces.

    The **failover arm** boots a 4-worker supervised fleet
    (service/fleet.py), compile-warms every slot through its ring tenant,
    then arms ``fleet.worker_kill``: the timed query's routed worker is
    SIGKILLed with the request on its pipe, and the wall runs until a
    *survivor* serves the journal-replayed attempt.  The **cold arm** is
    what a non-supervised serve deployment pays for the same death: a
    fresh supervisor restarted over a journal holding that unacknowledged
    intent, with the wall covering worker boot + replay + cold compile.

    Exit 3 unless both arms are oracle-exact, the failover attempt count
    proves a real mid-query death (attempts >= 2), both drains report the
    journal fully acknowledged with ``double_exec == 0`` (the
    exactly-once invariant), and failover beats the cold restart.  The
    BENCH headline ``value`` is the wall ratio (cold restart over
    failover, higher is better); ``failover_ms`` / ``cold_restart_ms`` /
    ``failover`` / ``replayn`` / ``jdepth`` / ``wincarn`` /
    ``worker_restarts`` / ``double_exec`` gate lower-is-better under
    tools_check_regress.py (``double_exec`` pins to zero: any growth from
    a zero base is an infinite delta)."""
    from tpu_radix_join.utils.platform import force_host_cpu_devices
    force_host_cpu_devices(8, respect_existing=True)

    import tempfile

    from tpu_radix_join.performance import Measurements
    from tpu_radix_join.performance.measurements import (FAILOVER, JDEPTH,
                                                         REPLAYN, WINCARN)
    from tpu_radix_join.robustness import faults
    from tpu_radix_join.service.fleet import FleetSupervisor, route_tenant
    from tpu_radix_join.service.journal import QueryJournal

    nodes = 1                   # single-device workers: boot cost is the
    expect = tpn * nodes        # jax import + one compile, not the mesh
    worker_args = ["--nodes", str(nodes), "--verify", "check"]

    def req(qid, tenant):
        return {"query_id": qid, "tenant": tenant,
                "tuples_per_node": tpn, "seed": 7}

    tmp = tempfile.mkdtemp(prefix="fleet_bench_")

    # ---- failover arm: warm fleet, SIGKILL the routed worker mid-query
    m = Measurements()
    sup = FleetSupervisor(workers, worker_args,
                          os.path.join(tmp, "failover"),
                          measurements=m, lease_s=1.0)
    try:
        sup.start()
        # one tenant per ring slot so every worker compile-warms before
        # the timed kill — the failover lands on a warm survivor, which
        # is the steady-state a supervised fleet actually runs in
        slots = list(range(workers))
        tenant_for = {}
        i = 0
        while len(tenant_for) < workers and i < 10000:
            t = f"t{i}"
            tenant_for.setdefault(route_tenant(t, slots), t)
            i += 1
        if len(tenant_for) < workers:
            print(f"ERROR: ring left slots tenant-less: {sorted(tenant_for)}",
                  file=sys.stderr)
            return 3
        for s in sorted(tenant_for):
            out = sup.dispatch(req(f"warm_w{s}", tenant_for[s]))
            if not (out.get("status") == "ok"
                    and out.get("matches") == expect):
                print(f"ERROR: warm-up on worker {s} not oracle-exact: "
                      f"{out.get('status')} matches={out.get('matches')} "
                      f"!= {expect}", file=sys.stderr)
                return 3
        victim = sorted(tenant_for)[0]
        with faults.FaultInjector(seed=11, measurements=m).arm(
                faults.FLEET_WORKER_KILL, at=1):
            t0 = time.perf_counter()
            out = sup.dispatch(req("kill", tenant_for[victim]))
            failover_ms = (time.perf_counter() - t0) * 1e3
        fleet = out.get("fleet") or {}
        if not (out.get("status") == "ok" and out.get("matches") == expect):
            print(f"ERROR: failover outcome not oracle-exact: "
                  f"{out.get('status')} matches={out.get('matches')} "
                  f"!= {expect} ({out.get('detail')})", file=sys.stderr)
            return 3
        if fleet.get("attempts", 1) < 2 or fleet.get("worker") == victim:
            print(f"ERROR: no real failover happened: served by worker "
                  f"{fleet.get('worker')} in {fleet.get('attempts')} "
                  f"attempt(s) (victim was {victim})", file=sys.stderr)
            return 3
        report = sup.drain()
    finally:
        sup.close()
    if report["unacked"] or report["double_exec"]:
        print(f"ERROR: failover arm broke exactly-once at drain: "
              f"{report}", file=sys.stderr)
        return 3

    # ---- cold arm: supervisor restart over a journal with the same
    # death's unacknowledged intent — boot + replay + cold compile
    cold_dir = os.path.join(tmp, "cold")
    QueryJournal(cold_dir).append_intent(req("cold_kill", "t0"))
    m2 = Measurements()
    sup2 = FleetSupervisor(workers, worker_args, cold_dir,
                           measurements=m2, lease_s=1.0)
    try:
        t0 = time.perf_counter()
        sup2.start()
        replayed = sup2.replay_unacknowledged()
        cold_ms = (time.perf_counter() - t0) * 1e3
        report2 = sup2.drain()
    finally:
        sup2.close()
    if not (len(replayed) == 1 and replayed[0].get("status") == "ok"
            and replayed[0].get("matches") == expect):
        print(f"ERROR: cold-restart replay not oracle-exact: {replayed}",
              file=sys.stderr)
        return 3
    if report2["unacked"] or report2["double_exec"]:
        print(f"ERROR: cold arm broke exactly-once at drain: {report2}",
              file=sys.stderr)
        return 3

    speedup = cold_ms / max(failover_ms, 1e-9)
    if speedup <= 1.0:
        print(f"ERROR: failover was not faster than the cold restart: "
              f"{failover_ms:.0f} ms vs {cold_ms:.0f} ms", file=sys.stderr)
        return 3
    print(f"note: kill-1-of-{workers}: failover {failover_ms:.0f} ms "
          f"(survivor, attempt {fleet.get('attempts')}) vs cold "
          f"supervisor restart {cold_ms:.0f} ms -> {speedup:.2f}x",
          file=sys.stderr)

    result = {
        "metric": "fleet_failover_speedup",
        "value": round(speedup, 3),
        "unit": "cold_restart_over_failover_wall",
        "workers": workers,
        "queries": sup.queries,
        "failover_ms": round(failover_ms, 1),
        "cold_restart_ms": round(cold_ms, 1),
        "failover": int(m.counters.get(FAILOVER, 0)),
        "replayn": int(m.counters.get(REPLAYN, 0)),
        "jdepth": int(m.counters.get(JDEPTH, 0)),
        "wincarn": int(m.counters.get(WINCARN, 0)),
        "worker_restarts": sup.restarts,
        "double_exec": report["double_exec"] + report2["double_exec"],
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        return code
    return 0


def main():
    # regression-gate post-step: parsed before any backend work so a typo'd
    # flag fails fast instead of after a multi-minute timed run
    check_baseline = None
    argv = sys.argv[1:]
    # forensics bundles (observability/postmortem.py): every bench death
    # path — chaos violations, backend-probe exhaustion — drops one here
    global _LEDGER_DIR
    _LEDGER_DIR = os.environ.get("TPU_RADIX_LEDGER_DIR")
    if "--ledger-dir" in argv:
        i = argv.index("--ledger-dir")
        if i + 1 >= len(argv):
            print("error: --ledger-dir needs a directory path",
                  file=sys.stderr)
            sys.exit(2)
        _LEDGER_DIR = argv[i + 1]
    forensics_dir = os.environ.get("TPU_RADIX_FORENSICS_DIR")
    if "--forensics-dir" in argv:
        i = argv.index("--forensics-dir")
        if i + 1 >= len(argv):
            print("error: --forensics-dir needs a directory path",
                  file=sys.stderr)
            sys.exit(2)
        forensics_dir = argv[i + 1]
    if "--chaos" in argv:
        # chaos soak mode (robustness/chaos.py): N seeded fault schedules
        # with verification always on, every run must pass or classify;
        # a violating schedule is ddmin-shrunk to a minimal (seed, arms)
        # repro.  CPU-sized and exits before the chip-reservation
        # machinery — it validates failure semantics, not throughput.
        i = argv.index("--chaos")
        try:
            runs = int(argv[i + 1])
        except (IndexError, ValueError):
            print("error: --chaos needs an integer run count",
                  file=sys.stderr)
            sys.exit(2)
        base_seed = (int(argv[argv.index("--chaos-seed") + 1])
                     if "--chaos-seed" in argv else 0)
        sys.exit(_run_chaos(runs, base_seed=base_seed,
                            forensics_dir=forensics_dir))
    if "--check-regress" in argv:
        i = argv.index("--check-regress")
        if i + 1 >= len(argv):
            print("error: --check-regress needs a baseline path",
                  file=sys.stderr)
            sys.exit(2)
        check_baseline = argv[i + 1]
        if not os.path.exists(check_baseline):
            print(f"error: baseline {check_baseline} not found",
                  file=sys.stderr)
            sys.exit(2)
    if "--static-gate" in argv:
        # merged static-analysis gate (tools_static_gate.py): graftlint
        # AST conventions + graftcheck jaxpr IR audit, both strict,
        # device-free — gates program invariants, not throughput.  Rides
        # bench so CI rigs that only know bench entry points can run it.
        import tools_static_gate
        gate_args = []
        if "--static-gate-json" in argv:
            i = argv.index("--static-gate-json")
            if i + 1 >= len(argv):
                print("error: --static-gate-json needs a file path",
                      file=sys.stderr)
                sys.exit(2)
            gate_args = ["--json", argv[i + 1]]
        sys.exit(tools_static_gate.main(gate_args))
    if "--grid-bench" in argv:
        # like --chaos: CPU-sized, exits before the chip-reservation
        # machinery — it gates the pipelined grid engine, not the chip
        sys.exit(_run_grid_bench(check_baseline))
    if "--exchange-bench" in argv:
        # wire-format A/B (data/tuples.py codec + parallel/window.py
        # staging): CPU-sized like --grid-bench — it gates exchange bytes
        # and the live exchange footprint, not chip throughput
        sys.exit(_run_exchange_bench(check_baseline))
    if "--partition-bench" in argv:
        # destination-grouping A/B (ops/pallas/partition.py vs the sort
        # scatter): CPU-sized like --grid-bench — it gates the fused
        # partition kernel's speedup and unit constant, not chip throughput
        sys.exit(_run_partition_bench(check_baseline))
    if "--sort-bench" in argv:
        # flat-sort A/B (ops/pallas/radix_sort.py vs lax.sort): CPU-sized
        # like --grid-bench — it gates the LSD radix kernel's correctness,
        # pass-skipping, and unit constant, not chip throughput
        sys.exit(_run_sort_bench(check_baseline))
    if "--recovery-bench" in argv:
        # elastic-recovery A/B (robustness/recovery.py): CPU-sized like
        # --chaos/--grid-bench — it gates kill-1-of-8 partition-level
        # recovery against the cold restart, not chip throughput.
        # --grow switches to the mid-run-admission-vs-fixed-survivors
        # arm; --straggle f to the hedged-vs-unhedged tail arm at
        # slowdown factor f (robustness/straggler.py)
        if "--grow" in argv:
            sys.exit(_run_recovery_grow_bench(check_baseline))
        if "--straggle" in argv:
            i = argv.index("--straggle")
            try:
                factor = float(argv[i + 1])
            except (IndexError, ValueError):
                print("error: --straggle needs a numeric slowdown factor",
                      file=sys.stderr)
                sys.exit(2)
            sys.exit(_run_recovery_straggle_bench(check_baseline, factor))
        sys.exit(_run_recovery_bench(check_baseline))
    if "--critpath-bench" in argv:
        # critical-path attribution overhead A/B (observability/critpath
        # + statusz): CPU-sized like --grid-bench — it gates the
        # introspection plane's <1% overhead bar, not chip throughput
        sys.exit(_run_critpath_bench(check_baseline))
    if "--fleet-bench" in argv:
        # crash-only fleet failover A/B (service/fleet.py + journal.py):
        # CPU-sized like --chaos/--serve-bench — it gates kill-1-of-4
        # mid-query failover against the cold supervisor restart and the
        # journal's exactly-once drain audit, not chip throughput
        sys.exit(_run_fleet_bench(check_baseline))
    if "--serve-throughput-bench" in argv:
        # serving fast-path A/B (service/resultcache.py + microbatch.py +
        # resident.py + ops/merge_delta.py): CPU-sized like
        # --chaos/--serve-bench — it gates the cache/batch/delta speedups,
        # the mid-batch-kill exactly-once audit, and statusz liveness,
        # not chip throughput
        sys.exit(_run_serve_throughput_bench(check_baseline))
    if "--serve-bench" in argv:
        # resident-service amortization bench (service/session.py):
        # CPU-sized like --chaos/--grid-bench — it gates warm-query reuse
        # and breaker recovery semantics, not chip throughput
        i = argv.index("--serve-bench")
        queries = 20
        if i + 1 < len(argv) and argv[i + 1].isdigit():
            queries = int(argv[i + 1])
        if queries < 2:
            print("error: --serve-bench needs at least 2 queries "
                  "(one cold, one warm)", file=sys.stderr)
            sys.exit(2)
        sys.exit(_run_serve_bench(check_baseline, queries=queries,
                                  chaos="--serve-chaos" in argv))

    size = 1 << 24               # 16M tuples per side
    planned = _planned_strategy(size, iters=20)
    _wait_for_backend(planned, forensics_dir=forensics_dir)
    # Cooperative chip reservation: long-running grid experiments
    # (chunked_join_grid) park between chunk pairs while this PID-stamped
    # file exists, so a background out-of-core run on the shared single
    # chip cannot contaminate the official benchmark's timings.  The
    # reciprocal GRID_RUNNING file tells us whether any live grid actually
    # holds the chip — only then is a drain wait paid, bounded by the
    # longest single chunk pair.
    import atexit

    from tpu_radix_join.utils.locks import (
        acquire_pid_file, bench_pause_file, grid_presence_file,
        pid_file_alive, remove_pid_file)
    pause_file = bench_pause_file()
    # atomic acquisition: a concurrent live bench (the runner's task racing
    # the driver's official capture) makes us wait; two simultaneous starts
    # cannot both win the O_EXCL create
    status = acquire_pid_file(pause_file, timeout_s=900, poll_s=15)
    if status == "acquired":
        atexit.register(remove_pid_file, pause_file)
    elif status == "busy":
        print("WARNING: another live bench still holds the chip after the "
              "wait deadline — timings below may be contaminated",
              file=sys.stderr)
        # keep contending in the background: the moment the peer exits we
        # stamp the reservation, so the grid stays parked for the rest of
        # this run instead of unparking mid-timed-window
        import threading

        # Shutdown handshake: a daemon thread dies unjoined at interpreter
        # exit, so an acquisition that lands while the process is tearing
        # down would leak a stamp no atexit can clean — the grid would stay
        # parked until its stale-PID sweep.  The main thread sets this event
        # at exit; the contender re-checks it around every acquisition and
        # releases immediately when it lost the race.
        bench_done = threading.Event()
        atexit.register(bench_done.set)

        def _contend():
            while not bench_done.is_set():
                if acquire_pid_file(pause_file, timeout_s=60,
                                    poll_s=15) != "acquired":
                    continue
                if bench_done.is_set():
                    remove_pid_file(pause_file)
                else:
                    atexit.register(remove_pid_file, pause_file)
                return

        threading.Thread(target=_contend, daemon=True).start()
    else:
        print(f"WARNING: could not stamp the chip reservation file "
              f"({pause_file} unwritable); grid runs will not park",
              file=sys.stderr)
    grid_file = grid_presence_file()

    def _grid_busy():
        return (pid_file_alive(grid_file)
                and not os.path.exists(grid_file + ".parked"))

    drain_deadline = time.monotonic() + 600
    while _grid_busy() and time.monotonic() < drain_deadline:
        print("note: live grid run holds the chip; draining...",
              file=sys.stderr)
        time.sleep(10)
    if _grid_busy():
        print("WARNING: grid run still mid-chunk-pair after the drain "
              "deadline — timings below may be contaminated by chip "
              "contention", file=sys.stderr)

    import jax
    import jax.numpy as jnp
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.ops.merge_count import merge_count_chunks, merge_count_pallas

    r_rel = Relation(size, 1, "unique", seed=1)
    s_rel = Relation(size, 1, "unique", seed=2)
    r = jax.block_until_ready(r_rel.shard(0))
    s = jax.block_until_ready(s_rel.shard(0))

    candidates = [("xla", jax.jit(merge_count_chunks))]
    run_pallas = jax.jit(merge_count_pallas)
    try:
        counts = run_pallas(r.key, s.key)
        pallas_matches = int(np.asarray(counts).astype(np.uint64).sum())
        if pallas_matches == size:
            candidates.append(("pallas", run_pallas))
        else:
            # a kernel that runs but miscounts is a correctness regression —
            # surface it loudly while letting the XLA path carry the bench
            print(f"WARNING: pallas path miscounts ({pallas_matches} != {size})",
                  file=sys.stderr)
    except Exception as e:
        print(f"note: pallas path unavailable ({type(e).__name__}); using XLA",
              file=sys.stderr)

    best = None
    for name, fn in candidates:
        if name != "pallas":   # pallas was already validated above
            counts = fn(r.key, s.key)
            matches = int(np.asarray(counts).astype(np.uint64).sum())
            assert matches == size, (name, matches, size)
        dt = _time_amortized(fn, (r.key, s.key))
        print(f"note: {name}: {dt*1e3:.1f} ms/iter", file=sys.stderr)
        if best is None or dt < best[1]:
            best = (name, dt)
    dt = best[1]

    # Full HashJoin pipeline at nodes=1 (compiled executable, amortized):
    # the driver-visible rate, not just the probe op.  Reported as a note —
    # the headline metric stays the probe for round-over-round comparability.
    try:
        from tpu_radix_join import HashJoin, JoinConfig
        eng = HashJoin(JoinConfig(num_nodes=1))
        rb = eng._place(r_rel)
        sb = eng._place(s_rel)
        jax.block_until_ready((rb, sb))
        cap_r, cap_s, _ = eng._measure_capacities(
            rb, sb, shuffles=not eng._single_node_sort_probe())
        fn = eng._get_compiled(rb, sb, cap_r, cap_s)
        counts, flags = fn(rb, sb)
        flags = np.asarray(flags)
        pipe_matches = int(np.asarray(counts).astype(np.uint64).sum())
        if pipe_matches != size:
            print(f"WARNING: pipeline miscounts ({pipe_matches} != {size})",
                  file=sys.stderr)
        elif flags.any():
            print(f"WARNING: pipeline failure flags {flags.tolist()}",
                  file=sys.stderr)
        else:
            pdt = _time_amortized(lambda a, b: fn(a, b)[0], (rb, sb))
            print(f"note: full_pipeline: {pdt*1e3:.1f} ms/iter "
                  f"({2*size/pdt/1e9:.3f} G tuples/s)", file=sys.stderr)
    except Exception as e:
        print(f"note: pipeline timing unavailable ({type(e).__name__}: {e})",
              file=sys.stderr)

    # Wide-key (64-bit) fused Pallas kernel: hardware validation + timing
    # (r2 weak #3 — interpret-mode-only until now).  Hi lanes derived the
    # same way Relation(key_bits=64) derives them.
    try:
        from tpu_radix_join.data.relation import key_hi_lane
        from tpu_radix_join.ops.merge_count import (
            merge_count_wide_per_partition)
        r_hi = key_hi_lane(r.key)
        s_hi = key_hi_lane(s.key)

        def wide(impl):
            return jax.jit(lambda a, b, c, d: merge_count_wide_per_partition(
                a, b, c, d, 5, impl=impl))

        args = (r.key, r_hi, s.key, s_hi)
        fp, fx = wide("pallas"), wide("xla")
        # validation calls double as compile warmup for the timed fn objects
        cp = np.asarray(fp(*args)).astype(np.uint64)
        cx = np.asarray(fx(*args)).astype(np.uint64)
        if not np.array_equal(cp, cx):
            print(f"WARNING: wide pallas != xla ({cp.sum()} vs {cx.sum()})",
                  file=sys.stderr)
        elif cp.sum() != size:
            print(f"WARNING: wide kernels miscount ({cp.sum()} != {size})",
                  file=sys.stderr)
        else:
            dtp = _time_amortized(fp, args)
            dtx = _time_amortized(fx, args)
            print(f"note: wide_pallas: {dtp*1e3:.1f} ms/iter (== xla counts); "
                  f"wide_xla: {dtx*1e3:.1f} ms/iter", file=sys.stderr)
    except Exception as e:
        print(f"note: wide kernel bench unavailable "
              f"({type(e).__name__}: {e})", file=sys.stderr)

    # Weighted (masked) Pallas histogram: backs the skew spread-demand pass
    try:
        from tpu_radix_join.ops.radix import local_histogram
        pid = r.key & jnp.uint32(31)
        mask = (r.key & jnp.uint32(1)).astype(bool)

        def hist(impl):
            return jax.jit(lambda p, w: local_histogram(p, 32, valid=w,
                                                        impl=impl))

        hfp, hfx = hist("pallas"), hist("xla")
        hp = np.asarray(hfp(pid, mask))
        hx = np.asarray(hfx(pid, mask))
        if not np.array_equal(hp, hx):
            print("WARNING: weighted histogram pallas != xla", file=sys.stderr)
        else:
            dth = _time_amortized(hfp, (pid, mask))
            print(f"note: weighted_histogram_pallas: {dth*1e3:.1f} ms/iter "
                  f"(== xla)", file=sys.stderr)
    except Exception as e:
        print(f"note: weighted histogram bench unavailable "
              f"({type(e).__name__}: {e})", file=sys.stderr)

    tuples_per_sec = (2 * size) / dt   # both relations processed
    # Bandwidth utilization of the dominant stage (VERDICT r4 #4): the
    # headline ratio now carries the number that justifies or indicts it —
    # how close the sort runs to the chip's measured HBM envelope.
    sort_gbps, sort_src = _sort_bandwidth_gbps(dt, size)
    print(f"note: sort stage ≈ {sort_gbps:.1f} GB/s vs ~105 GB/s sustained "
          f"envelope (traffic lower bound / time from {sort_src})",
          file=sys.stderr)
    result = {
        "metric": "single_chip_join_throughput",
        "value": round(tuples_per_sec, 1),
        "unit": "tuples/sec",
        "vs_baseline": round(tuples_per_sec / 1e9, 4),
        "size": size,
        "sort_gbps": round(sort_gbps, 1),
        "hbm_envelope_gbps": 105.0,
        "sort_gbps_source": sort_src,
        "planned_strategy": planned.get("strategy", "unknown"),
        "planned": planned,
    }
    print(json.dumps(result))
    _ledger_append(result)
    if check_baseline:
        from tpu_radix_join.observability.regress import check_result
        code, report = check_result(result, check_baseline)
        print(report, file=sys.stderr)
        if code:
            sys.exit(code)


if __name__ == "__main__":
    main()
