"""Benchmark driver: single-chip radix join throughput on real TPU.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Workload: the reference's canonical per-node join scaled to one chip —
16M ⋈ 16M dense unique uint32 keys (BASELINE.md config #2; the reference runs
20M ⋈ 20M per node, main.cpp:70-71).  Correctness is asserted against the
unique-key oracle before timing.

Timing methodology: the TPU in this environment sits behind a tunnel where
``jax.block_until_ready`` returns before execution finishes and a host
round-trip costs ~30-125ms.  So each candidate is jitted end-to-end, timed
over enough dispatches that compute dominates, and the clock stops on a real
host readback (np.asarray) of the final result.

vs_baseline: the reference publishes no numbers (BASELINE.md — published {}),
so the denominator is 1e9 tuples/sec/accelerator, a nominal figure for the
reference-era GPU build/probe kernels (sm_60-class, eth.cu) on this workload;
vs_baseline >= 1.0 therefore means beating reference-class per-accelerator
throughput.
"""

import json
import sys
import time

import numpy as np


def _time_amortized(fn, args, iters=20):
    """Seconds/iteration: ``iters`` async dispatches closed by one host
    readback (the only reliable sync through the tunnel)."""
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / iters


def main():
    # A downed axon tunnel makes jax.devices() block on a *native* futex that
    # a SIGALRM Python handler can never interrupt; probe the backend in a
    # child process with a hard timeout so the bench fails fast and loud
    # instead of hanging the driver forever.
    import subprocess
    try:
        # sitecustomize locks the platform default at import, so the child
        # re-applies any JAX_PLATFORMS override the same way the parent must
        probe = subprocess.run(
            [sys.executable, "-c",
             "import os, jax\n"
             "p = os.environ.get('JAX_PLATFORMS')\n"
             "p and jax.config.update('jax_platforms', p)\n"
             "print(jax.devices()[0])"],
            capture_output=True, text=True, timeout=120)
    except subprocess.TimeoutExpired:
        print("ERROR: device backend did not come up within 120s — the TPU "
              "tunnel hangs rather than failing when it is down; aborting",
              file=sys.stderr)
        sys.exit(2)
    if probe.returncode != 0:
        print(f"ERROR: device backend unavailable:\n{probe.stderr.strip()}",
              file=sys.stderr)
        sys.exit(2)
    print(f"note: device: {probe.stdout.strip()}", file=sys.stderr)

    import jax
    import jax.numpy as jnp
    from tpu_radix_join.data.relation import Relation
    from tpu_radix_join.ops.merge_count import merge_count_chunks, merge_count_pallas

    size = 1 << 24               # 16M tuples per side

    r_rel = Relation(size, 1, "unique", seed=1)
    s_rel = Relation(size, 1, "unique", seed=2)
    r = jax.block_until_ready(r_rel.shard(0))
    s = jax.block_until_ready(s_rel.shard(0))

    candidates = [("xla", jax.jit(merge_count_chunks))]
    run_pallas = jax.jit(merge_count_pallas)
    try:
        counts = run_pallas(r.key, s.key)
        pallas_matches = int(np.asarray(counts).astype(np.uint64).sum())
        if pallas_matches == size:
            candidates.append(("pallas", run_pallas))
        else:
            # a kernel that runs but miscounts is a correctness regression —
            # surface it loudly while letting the XLA path carry the bench
            print(f"WARNING: pallas path miscounts ({pallas_matches} != {size})",
                  file=sys.stderr)
    except Exception as e:
        print(f"note: pallas path unavailable ({type(e).__name__}); using XLA",
              file=sys.stderr)

    best = None
    for name, fn in candidates:
        if name != "pallas":   # pallas was already validated above
            counts = fn(r.key, s.key)
            matches = int(np.asarray(counts).astype(np.uint64).sum())
            assert matches == size, (name, matches, size)
        dt = _time_amortized(fn, (r.key, s.key))
        print(f"note: {name}: {dt*1e3:.1f} ms/iter", file=sys.stderr)
        if best is None or dt < best[1]:
            best = (name, dt)
    dt = best[1]

    # Full HashJoin pipeline at nodes=1 (compiled executable, amortized):
    # the driver-visible rate, not just the probe op.  Reported as a note —
    # the headline metric stays the probe for round-over-round comparability.
    try:
        from tpu_radix_join import HashJoin, JoinConfig
        eng = HashJoin(JoinConfig(num_nodes=1))
        rb = eng._place(r_rel)
        sb = eng._place(s_rel)
        jax.block_until_ready((rb, sb))
        cap_r, cap_s, _ = eng._measure_capacities(
            rb, sb, shuffles=not eng._single_node_sort_probe())
        fn = eng._get_compiled(rb, sb, cap_r, cap_s)
        counts, flags = fn(rb, sb)
        flags = np.asarray(flags)
        pipe_matches = int(np.asarray(counts).astype(np.uint64).sum())
        if pipe_matches != size:
            print(f"WARNING: pipeline miscounts ({pipe_matches} != {size})",
                  file=sys.stderr)
        elif flags.any():
            print(f"WARNING: pipeline failure flags {flags.tolist()}",
                  file=sys.stderr)
        else:
            pdt = _time_amortized(lambda a, b: fn(a, b)[0], (rb, sb))
            print(f"note: full_pipeline: {pdt*1e3:.1f} ms/iter "
                  f"({2*size/pdt/1e9:.3f} G tuples/s)", file=sys.stderr)
    except Exception as e:
        print(f"note: pipeline timing unavailable ({type(e).__name__}: {e})",
              file=sys.stderr)

    # Wide-key (64-bit) fused Pallas kernel: hardware validation + timing
    # (r2 weak #3 — interpret-mode-only until now).  Hi lanes derived the
    # same way Relation(key_bits=64) derives them.
    try:
        from tpu_radix_join.data.relation import key_hi_lane
        from tpu_radix_join.ops.merge_count import (
            merge_count_wide_per_partition)
        r_hi = key_hi_lane(r.key)
        s_hi = key_hi_lane(s.key)

        def wide(impl):
            return jax.jit(lambda a, b, c, d: merge_count_wide_per_partition(
                a, b, c, d, 5, impl=impl))

        args = (r.key, r_hi, s.key, s_hi)
        fp, fx = wide("pallas"), wide("xla")
        # validation calls double as compile warmup for the timed fn objects
        cp = np.asarray(fp(*args)).astype(np.uint64)
        cx = np.asarray(fx(*args)).astype(np.uint64)
        if not np.array_equal(cp, cx):
            print(f"WARNING: wide pallas != xla ({cp.sum()} vs {cx.sum()})",
                  file=sys.stderr)
        elif cp.sum() != size:
            print(f"WARNING: wide kernels miscount ({cp.sum()} != {size})",
                  file=sys.stderr)
        else:
            dtp = _time_amortized(fp, args)
            dtx = _time_amortized(fx, args)
            print(f"note: wide_pallas: {dtp*1e3:.1f} ms/iter (== xla counts); "
                  f"wide_xla: {dtx*1e3:.1f} ms/iter", file=sys.stderr)
    except Exception as e:
        print(f"note: wide kernel bench unavailable "
              f"({type(e).__name__}: {e})", file=sys.stderr)

    # Weighted (masked) Pallas histogram: backs the skew spread-demand pass
    try:
        from tpu_radix_join.ops.radix import local_histogram
        pid = r.key & jnp.uint32(31)
        mask = (r.key & jnp.uint32(1)).astype(bool)

        def hist(impl):
            return jax.jit(lambda p, w: local_histogram(p, 32, valid=w,
                                                        impl=impl))

        hfp, hfx = hist("pallas"), hist("xla")
        hp = np.asarray(hfp(pid, mask))
        hx = np.asarray(hfx(pid, mask))
        if not np.array_equal(hp, hx):
            print("WARNING: weighted histogram pallas != xla", file=sys.stderr)
        else:
            dth = _time_amortized(hfp, (pid, mask))
            print(f"note: weighted_histogram_pallas: {dth*1e3:.1f} ms/iter "
                  f"(== xla)", file=sys.stderr)
    except Exception as e:
        print(f"note: weighted histogram bench unavailable "
              f"({type(e).__name__}: {e})", file=sys.stderr)

    tuples_per_sec = (2 * size) / dt   # both relations processed
    print(json.dumps({
        "metric": "single_chip_join_throughput",
        "value": round(tuples_per_sec, 1),
        "unit": "tuples/sec",
        "vs_baseline": round(tuples_per_sec / 1e9, 4),
    }))


if __name__ == "__main__":
    main()
