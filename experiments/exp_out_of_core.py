"""At-scale out-of-core grid join on the real chip (the LD capability,
kernels.cu:563-858 / data.hpp iterCount, exercised at reference-exceeding
scale on ONE device).

128M ⋈ 128M unique tuples (8x the 16M bench config; 2 GB of key+rid lanes
per side at full residency — the grid join holds only O(chunk) instead),
both sides **device-generated** per chunk (data/streaming.stream_chunks_device)
so the run measures the join engine, not the host attachment.  Exact oracle:
unique ⋈ unique over the same range must count exactly GLOBAL matches.

    python experiments/exp_out_of_core.py [global_log2=27] [chunk_log2=24] [key_bits=32]

``global_log2 >= 31`` requires ``key_bits=64`` (the BASELINE config #5 shape:
1B ⋈ 1B wide keys — ``python ... 30 26 64`` runs the full billion-scale grid
on one chip, out of core).

Checkpointed (VERDICT r3 weak #1): every completed (inner, outer) chunk pair
is persisted under artifacts/oo_ckpt/, so a tunnel drop mid-grid resumes at
the next pair on rerun instead of restarting — the round-3 run died with the
tunnel and lost everything; this one cannot.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from tpu_radix_join.utils.platform import apply_platform_override

apply_platform_override()   # honor JAX_PLATFORMS (e.g. CPU smoke runs)

# cooperative chip yield: bench.py holds BENCH_RUNNING during its timed
# window and the grid parks between chunk pairs, advertising GRID_RUNNING
# (+ .parked while yielded); both sides resolve the paths through
# utils/locks.py, so no per-experiment wiring is needed here
from tpu_radix_join.data.relation import Relation
from tpu_radix_join.data.streaming import stream_chunks_device
from tpu_radix_join.ops.chunked import chunked_join_grid


def main() -> int:
    glog = int(sys.argv[1]) if len(sys.argv) > 1 else 27
    clog = int(sys.argv[2]) if len(sys.argv) > 2 else 24
    key_bits = int(sys.argv[3]) if len(sys.argv) > 3 else 32
    size, chunk = 1 << glog, 1 << clog
    print(f"device: {jax.devices()[0]}, global: {size:,} x {size:,}, "
          f"chunk: {chunk:,} ({(size // chunk) ** 2} grid pairs), "
          f"key_bits: {key_bits}", flush=True)
    r = Relation(size, 1, "unique", seed=1, key_bits=key_bits)
    s = Relation(size, 1, "unique", seed=2, key_bits=key_bits)

    ckpt_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "oo_ckpt")
    os.makedirs(ckpt_dir, exist_ok=True)
    tag = f"oo_g{glog}_c{clog}_k{key_bits}_seeds12"
    ckpt = os.path.join(ckpt_dir, tag + ".json")
    if os.path.exists(ckpt):
        print(f"resuming from checkpoint {ckpt}", flush=True)

    t0 = time.perf_counter()
    # both sides as generators: chunked_join_grid consumes the inner side
    # exactly once and re-streams the outer per inner chunk, so device
    # residency stays O(chunk) — required at the billion-scale config
    total = chunked_join_grid(
        stream_chunks_device(r, 0, chunk),
        lambda: stream_chunks_device(s, 0, chunk),
        slab_size=chunk,
        checkpoint_path=ckpt, checkpoint_tag=tag, progress=True,
        # unique Relations cap keys below 2**31 (relation.py size guard):
        # the narrow hint skips the per-pair max-key probe on 32-bit grids
        key_range="narrow" if key_bits == 32 else "auto")
    dt = time.perf_counter() - t0
    ok = total == size
    print(f"matches: {total:,} expected: {size:,} "
          f"({'OK' if ok else 'MISMATCH'})")
    print(f"wall: {dt:.1f} s  ({2 * size / dt / 1e6:.1f} M tuples/s "
          f"end-to-end; the grid probes {(size // chunk)} x the outer side, "
          f"so probe work is {(size // chunk)}x a resident join's; resumed "
          f"runs report only the remaining pairs' wall time)")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
