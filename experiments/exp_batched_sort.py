"""Round-2 experiment: can a bucketize + batched-sort discipline beat the
flat-sort merge count (VERDICT #1)?

Measures, on the real chip:
  1. flat lax.sort at 33.5M uint32 (round-1 figure: 51.9 ms)
  2. batched sort at several row lengths (round-1: [4096, 8192] = 25.0 ms)
  3. multi-operand sort cost (the bucketize permutation carrier)
  4. the hypothetical best case: probe_count_bucketized_merge on
     pre-bucketized rows (what we'd get if bucketization were free)
  5. end-to-end merge_count_chunks (round-1 bench: ~48 ms/iter)

Methodology: amortized async dispatches closed by one host readback
(bench.py); per-dispatch tunnel round-trip ~5-8 ms does not pipeline.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=10):
    out = fn(*args)           # warm/compile
    np.asarray(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / iters


def main():
    n = 1 << 25               # 33.5M — the merge-count union size for 16M x 16M
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 31, size=n, dtype=np.uint32)
    x = jax.device_put(jnp.asarray(keys))
    jax.block_until_ready(x)

    sort1 = jax.jit(lambda a: jax.lax.sort((a,), is_stable=False)[0])
    print(f"flat sort {n}: {timeit(sort1, x)*1e3:.1f} ms")

    for rows in (64, 512, 4096, 8192, 16384, 32768):
        cols = n // rows
        xb = x.reshape(rows, cols)
        sortb = jax.jit(lambda a: jax.lax.sort((a,), dimension=1,
                                               is_stable=False)[0])
        print(f"batched sort [{rows}, {cols}]: {timeit(sortb, xb)*1e3:.1f} ms")

    # multi-operand flat sort: 1 key + k carried lanes
    v = jax.device_put(jnp.arange(n, dtype=jnp.uint32))
    sort2 = jax.jit(lambda a, b: jax.lax.sort((a, b), is_stable=False)[1])
    print(f"flat sort kv (2 lanes): {timeit(sort2, x, v)*1e3:.1f} ms")
    sort3 = jax.jit(lambda a, b, c: jax.lax.sort((a, b, c), is_stable=False)[1])
    print(f"flat sort kvv (3 lanes): {timeit(sort3, x, v, v)*1e3:.1f} ms")

    # batched 2-key lexicographic sort (the bucketized probe's inner op)
    for rows in (2048, 4096):
        cols = n // rows
        xb = x.reshape(rows, cols)
        tb = v.reshape(rows, cols)
        sortlex = jax.jit(lambda a, b: jax.lax.sort(
            (a, b), dimension=1, is_stable=False, num_keys=2)[0])
        print(f"batched 2-key sort [{rows}, {cols}]: "
              f"{timeit(sortlex, xb, tb)*1e3:.1f} ms")

    # hypothetical best case: rows pre-bucketized, count via batched sort-merge
    from tpu_radix_join.ops.build_probe import probe_count_bucketized_merge
    nb = 2048
    cap = (1 << 24) // nb * 2          # 2x slack per bucket row
    rk = rng.integers(0, 1 << 31, size=(nb, cap), dtype=np.uint32)
    sk = rng.integers(0, 1 << 31, size=(nb, cap), dtype=np.uint32)
    rb = jax.device_put(jnp.asarray(rk))
    sb = jax.device_put(jnp.asarray(sk))
    pc = jax.jit(probe_count_bucketized_merge)
    print(f"bucketized merge-count [{nb}, {cap}] x2 (pre-bucketized): "
          f"{timeit(pc, rb, sb)*1e3:.1f} ms")

    # end-to-end current champion
    from tpu_radix_join.ops.merge_count import merge_count_chunks
    half = n // 2
    r = x[:half]
    s = x[half:]
    mc = jax.jit(merge_count_chunks)
    print(f"merge_count_chunks 16M x 16M: {timeit(mc, r, s)*1e3:.1f} ms")


if __name__ == "__main__":
    main()
