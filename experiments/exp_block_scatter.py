"""On-chip measurement: scatter_to_blocks inner discipline (VERDICT r2 #8).

The send half of every shuffle and local partition routes sorted runs into
fixed-capacity blocks.  Two exact implementations (ops/radix.py):

  * "loop"   — fori_loop of per-destination dynamic-slice copies
               (num_blocks sequential DMAs; the round-1/2 shipping path);
  * "gather" — one vectorized row gather over the [num_blocks, capacity]
               grid (no sequential dependency).

The reference tunes the same inner loop with SWWC buffers + AVX streams
(NetworkPartitioning.cpp:224-260).  Run ON THE REAL CHIP:

    python experiments/exp_block_scatter.py

Prints ms/iter for both impls at N=32 and N=64 on a 16M-tuple relation and
asserts they produce identical blocks.  Measured results live in
PERF_NOTES.md; the winner is scatter_to_blocks' default.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

from tpu_radix_join.data.tuples import TupleBatch
from tpu_radix_join.ops.radix import scatter_to_blocks


def _time(fn, args, iters=20):
    out = fn(*args)               # compile + correctness reference
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(out[1])            # host readback closes the async window
    return (time.perf_counter() - t0) / iters, out


def main():
    size = 1 << 24
    rng = np.random.default_rng(0)
    batch = TupleBatch(
        key=jnp.asarray(rng.integers(0, 1 << 31, size, dtype=np.uint32)),
        rid=jnp.arange(size, dtype=jnp.uint32))
    print(f"device: {jax.devices()[0]}, tuples: {size}")
    for num_blocks in (32, 64):
        dest = batch.key % jnp.uint32(num_blocks)
        capacity = (size // num_blocks) * 2

        results = {}
        for impl in ("loop", "gather"):
            fn = jax.jit(
                lambda b, d, impl=impl: scatter_to_blocks(
                    b, d, num_blocks, capacity, "inner", impl=impl))
            dt, out = _time(fn, (batch, dest))
            results[impl] = (dt, out)
            print(f"N={num_blocks:3d} impl={impl:6s}: {dt*1e3:8.2f} ms/iter")
        (_, a), (_, b) = results["loop"], results["gather"]
        np.testing.assert_array_equal(np.asarray(a[0].key),
                                      np.asarray(b[0].key))
        np.testing.assert_array_equal(np.asarray(a[1]), np.asarray(b[1]))
        print(f"N={num_blocks:3d}: impls identical ok")


if __name__ == "__main__":
    main()
