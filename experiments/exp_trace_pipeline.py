"""Profiler-trace breakdown of the fused single-chip pipeline.

Produces the round-3 verdict's missing evidence (weak #2's last link): a
real-chip ``jax.profiler`` trace of the fused 16M ⋈ 16M pipeline parsed into
a per-op time breakdown (performance/trace.py), answering directly what
fraction of the pipeline is the sort — PERF_NOTES' sort-floor argument
predicts >= ~95%.

    python experiments/exp_trace_pipeline.py [log2_size=24] [out_dir]

Writes the raw trace plus ``breakdown.json`` (CTOTAL, per-op table, sort
share) under ``out_dir`` (default artifacts/chip_r4/trace_16m) and prints
the table.  The CTOTAL tag is the reference's PAPI total-cycles analog
(performance/Measurements.cpp:90-107).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from tpu_radix_join.utils.platform import apply_platform_override

apply_platform_override()   # honor JAX_PLATFORMS (e.g. CPU smoke runs)

import numpy as np

from tpu_radix_join import HashJoin, JoinConfig, Relation
from tpu_radix_join.performance import Measurements

ITERS = 8


def main() -> int:
    args = [a for a in sys.argv[1:] if a != "--two-level"]
    two_level = "--two-level" in sys.argv[1:]
    log2 = int(args[0]) if args else 24
    out_dir = args[1] if len(args) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "chip_r4", f"trace_{1 << log2 >> 20}m")
    size = 1 << log2
    print(f"device: {jax.devices()[0]}, size: {size:,}, out: {out_dir}, "
          f"two_level: {two_level}", flush=True)
    # --two-level: trace the bucket discipline's fused program instead — the
    # per-op table answers how its device time splits between the second
    # radix pass and the per-bucket probe (VERDICT r4 weak #3's "real work
    # vs round-trips" question, net of any dispatch entirely by design:
    # the trace sees only device ops).  Geometry stays at the JoinConfig
    # defaults so the traced executable is the SAME program as the
    # cli_16m_twolevel_fused timing run it explains.
    eng = HashJoin(JoinConfig(num_nodes=1, two_level=two_level))
    r = eng.place(Relation(size, 1, "unique", seed=1))
    s = eng.place(Relation(size, 1, "unique", seed=2))
    cap_r, cap_s, _ = eng._measure_capacities(
        r, s, shuffles=not eng._single_node_sort_probe())
    fn = eng._get_compiled(r, s, cap_r, cap_s)
    counts, flags = fn(r, s)                       # warm (compile cached)
    matches = int(np.asarray(counts).astype(np.uint64).sum())
    assert matches == size and not np.asarray(flags).any(), (matches, flags)

    m = Measurements()
    t0 = time.perf_counter()
    with m.trace(out_dir):
        for _ in range(ITERS):
            counts, flags = fn(r, s)
        np.asarray(counts)                         # host readback fence
    wall = time.perf_counter() - t0
    tr = m.meta.get("trace")
    if tr is None:
        print("ERROR: no parsable xplane artifact", flush=True)
        return 1

    busy = tr["busy_us"]
    sort_us = sum(v["us"] for name, v in tr["ops"].items()
                  if "sort" in name.lower())
    rows = [(name, v["us"], v["count"]) for name, v in tr["ops"].items()]
    print(f"plane: {tr['plane']}")
    print(f"CTOTAL (busy): {busy / 1e3:.1f} ms over {ITERS} iters "
          f"({busy / ITERS / 1e3:.1f} ms/iter; wall {wall * 1e3:.0f} ms)")
    print(f"sort share: {100.0 * sort_us / busy:.1f}% "
          f"({sort_us / ITERS / 1e3:.1f} ms/iter)")
    for name, us, cnt in rows[:15]:
        print(f"  {us / ITERS / 1e3:9.3f} ms/iter x{cnt:<4d} {name[:90]}")

    with open(os.path.join(out_dir, "breakdown.json"), "w") as f:
        json.dump({"size": size, "iters": ITERS, "plane": tr["plane"],
                   # discipline marker: bench._sort_bandwidth_gbps must only
                   # consume sort-path traces (absent key = legacy sort-path)
                   "discipline": "two_level" if two_level else "sort",
                   "busy_us": busy, "sort_share": sort_us / busy,
                   "ops": tr["ops"]}, f, indent=1)
    print(f"wrote {out_dir}/breakdown.json", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
