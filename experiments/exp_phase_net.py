"""Net-of-dispatch phase breakdown (VERDICT r4 #7): how much of a split
pipeline's phase columns is real device work vs the per-program host
dispatch round-trip the tunnel charges (~100 ms, recorded as SDISPATCH by
``Measurements.measure_dispatch_floor``).

    python experiments/exp_phase_net.py PHASES_DIR [FUSED_DIR]

``PHASES_DIR``: a ``--measure-phases`` experiment dir (e.g.
``artifacts/chip_r5/perf_16m_phases``).  Each split phase column runs as its
own program per repeat, so its gross host-clock time includes one dispatch
floor per repeat; the table prints gross, dispatches charged, and net.
With ``FUSED_DIR`` (the same workload's fused run) it also answers the
round-4 question directly: of the bucket path's gross JPROC-vs-fused gap,
how many ms are dispatch accounting vs real extra work.

The reference needs no such correction — its phases share one process and
PAPI brackets them without re-dispatch (Measurements.cpp:90-134); here the
split is the price of host-visible JMPI/JPROC columns (config.measure_phases).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json

from tpu_radix_join.performance.measurements import Measurements

# one host-dispatched program per repeat per column (hash_join._run_split:
# shuffle -> JMPI; bucket LP -> SLOCPREP; probe/BP chain -> JPROC; the
# sizing pre-pass -> JHIST).  BPBUILD/BPPROBE are sub-spans of the bucket
# JPROC chain's two programs.
_PROGRAMS_PER_REPEAT = {
    "JHIST": 1, "JMPI": 1, "SLOCPREP": 1, "JPROC": 1,
    "BPBUILD": 1, "BPPROBE": 1,
}


def _load(d):
    ms = Measurements.load(d)
    if not ms:
        raise SystemExit(f"no .perf files in {d}")
    m = ms[0]
    info_path = os.path.join(d, f"{m.node_id}.info")
    repeat = 1
    if os.path.exists(info_path):
        with open(info_path) as f:
            meta = json.load(f)
        repeat = int(meta.get("config", {}).get("repeat") or 1)
    return m, repeat


def main() -> int:
    if len(sys.argv) < 2:
        print(__doc__)
        return 2
    m, repeat = _load(sys.argv[1])
    floor = m.times_us.get("SDISPATCH", 0.0)
    if not floor:
        print("WARNING: no SDISPATCH tag in this perf dir; net == gross")
    print(f"dir: {sys.argv[1]}  repeats: {repeat}  "
          f"dispatch floor: {floor / 1e3:.1f} ms/program")
    print(f"{'phase':10s} {'gross ms':>10s} {'dispatches':>11s} "
          f"{'net ms':>10s} {'net ms/join':>12s}")
    nets = {}
    for tag, per_rep in _PROGRAMS_PER_REPEAT.items():
        gross = m.times_us.get(tag)
        if gross is None:
            continue
        charged = per_rep * repeat if tag not in ("BPBUILD", "BPPROBE") else 0
        net = max(0.0, gross - charged * floor)
        nets[tag] = net
        print(f"{tag:10s} {gross / 1e3:10.1f} {charged:11d} "
              f"{net / 1e3:10.1f} {net / repeat / 1e3:12.1f}")

    if len(sys.argv) > 2:
        f, f_rep = _load(sys.argv[2])
        f_gross = f.times_us.get("JPROC", 0.0)
        f_floor = f.times_us.get("SDISPATCH", floor)
        f_net = max(0.0, f_gross - f_rep * f_floor)
        split_work = sum(nets.get(t, 0.0)
                         for t in ("JMPI", "SLOCPREP", "JPROC"))
        split_gross = sum(m.times_us.get(t, 0.0)
                          for t in ("JMPI", "SLOCPREP", "JPROC"))
        print(f"\nfused dir: {sys.argv[2]}  JPROC gross "
              f"{f_gross / f_rep / 1e3:.1f} ms/join, net "
              f"{f_net / f_rep / 1e3:.1f} ms/join")
        gap_gross = split_gross / repeat - f_gross / f_rep
        gap_net = split_work / repeat - f_net / f_rep
        if gap_gross > 0:
            print(f"split-vs-fused gap: {gap_gross / 1e3:.1f} ms/join gross, "
                  f"{gap_net / 1e3:.1f} ms/join net of dispatch — "
                  f"{100 * (1 - gap_net / gap_gross):.0f}% of the gap is "
                  f"dispatch accounting")
    return 0


if __name__ == "__main__":
    sys.exit(main())
