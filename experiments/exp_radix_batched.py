"""Round-5 sort-floor attack (VERDICT r4 #5): the combined discipline —
radix-scatter the packed union into 64 pid blocks, batched row sorts of
n/64, fused per-block merge scan — measured end-to-end against the flat
champion (``merge_count_pallas``: one flat unstable sort + one Pallas pass).

Why this is THE remaining candidate: PERF_NOTES' round-2 primitive table
shows batched sorts at [64, 524288] cost 30.7 ms vs 47.7 ms flat at 33.5M,
i.e. bucketization wins IF it costs < ~17 ms.  Every binning engine was
priced individually (scatter-add 98 ms/16M, counting-sort DMA >= 361
stage-units, in-VMEM redistribution ~60 ms); this experiment runs the one
composition the verdict asked for, with the cheapest grouping engine the
hardware offers (the dest kv-sort + contiguous per-run DMA discipline of
``ops/radix.scatter_to_blocks``), and validates the count exactly.

The reference's counterpart shape is its two-pass partition-then-probe
(operators/gpu/kernels_optimized.cu:19-246): partition first, then many
small per-partition probes — on TPU the open question is only whether any
grouping pass undercuts the flat sort's 325 stage-units.

    python experiments/exp_radix_batched.py [log2_half=24]

Prints ms/iter for: flat champion, combined end-to-end, and the combined
path's stage decomposition (dest kv-sort / block DMA+mask / batched row
sort / scan), then an explicit WIN/DEAD-END verdict line for PERF_NOTES.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from tpu_radix_join.utils.platform import apply_platform_override

apply_platform_override()   # honor JAX_PLATFORMS (e.g. CPU smoke runs)

import jax.numpy as jnp
import numpy as np

from tpu_radix_join.ops.merge_count import (
    _S_PACK_PAD, _pack_pm, merge_count_chunks, merge_count_pallas)
from tpu_radix_join.ops.pallas.merge_scan import (
    TILE, merge_scan_chunks, pallas_available)
from tpu_radix_join.ops.sorting import sort_kv_unstable

FANOUT_BITS = 6                      # 64 blocks, the measured DMA sweet spot


def _time(fn, args, iters=10):
    out = fn(*args)                  # compile + warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0])   # readback closes the async window
    return (time.perf_counter() - t0) / iters


def _scan_count(flat: jnp.ndarray) -> jnp.ndarray:
    """Per-tile partial counts of a blockwise-sorted packed array.  Valid
    because pid occupies the top bits (_pack_pm), so equal packed keys never
    span block rows and pads carry zero weight wherever they sit."""
    pad = (-flat.shape[0]) % TILE
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.full((pad,), _S_PACK_PAD, jnp.uint32)])
    if pallas_available():
        return merge_scan_chunks(flat)
    from tpu_radix_join.ops.merge_count import _weights
    w, _ = _weights(flat)
    return jnp.sum(w.reshape(4096, -1), axis=1, dtype=jnp.uint32)


def _group_blocks(packed: jnp.ndarray, capacity: int):
    """Dest-grouping permutation + per-run DMA into [nb, capacity] rows
    (the scatter_to_blocks loop discipline, single lane)."""
    nb = 1 << FANOUT_BITS
    dest = packed >> jnp.uint32(32 - FANOUT_BITS)
    sdest, svals = sort_kv_unstable(dest, packed)
    bounds = jnp.searchsorted(
        sdest, jnp.arange(nb + 1, dtype=jnp.uint32)).astype(jnp.uint32)
    starts, counts = bounds[:-1], bounds[1:] - bounds[:-1]
    padded = jnp.concatenate(
        [svals, jnp.full((capacity,), _S_PACK_PAD, jnp.uint32)])

    def copy(d, out):
        return jax.lax.dynamic_update_slice(
            out, jax.lax.dynamic_slice(padded, (starts[d],), (capacity,)),
            (d * capacity,))

    out = jax.lax.fori_loop(0, nb, copy,
                            jnp.zeros((nb * capacity,), jnp.uint32))
    col = jnp.arange(capacity, dtype=jnp.uint32)[None, :]
    ok = (col < counts[:, None]).reshape(-1)
    rows = jnp.where(ok, out, jnp.uint32(_S_PACK_PAD)).reshape(nb, capacity)
    overflow = jnp.sum(jnp.maximum(counts, jnp.uint32(capacity))
                       - jnp.uint32(capacity))
    return rows, overflow


def combined_count(r_keys, s_keys, capacity):
    packed = _pack_pm(r_keys, s_keys, FANOUT_BITS)
    rows, overflow = _group_blocks(packed, capacity)
    rows = jax.lax.sort((rows,), dimension=1, is_stable=False)[0]
    return _scan_count(rows.reshape(-1)), overflow


def main():
    log2 = int(sys.argv[1]) if len(sys.argv) > 1 else 24
    half = 1 << log2
    n = 2 * half
    nb = 1 << FANOUT_BITS
    capacity = 2 * (n // nb)          # 2x mean slack; overflow-checked
    rng = np.random.default_rng(0)
    perm = rng.permutation(half).astype(np.uint32)
    r = jax.device_put(jnp.asarray(perm))
    s = jax.device_put(jnp.asarray(rng.permutation(half).astype(np.uint32)))
    jax.block_until_ready((r, s))
    print(f"device: {jax.devices()[0]}, union: {n:,}, "
          f"blocks: {nb} x {capacity}", flush=True)

    champion = jax.jit(merge_count_pallas if pallas_available()
                       else merge_count_chunks)
    cc = np.asarray(champion(r, s)).astype(np.uint64).sum()
    assert cc == half, (cc, half)
    t_flat = _time(champion, (r, s))
    print(f"flat champion (sort+scan):     {t_flat*1e3:8.2f} ms/iter")

    comb = jax.jit(lambda a, b: combined_count(a, b, capacity))
    counts, overflow = comb(r, s)
    ov = int(np.asarray(overflow))
    total = np.asarray(counts).astype(np.uint64).sum()
    assert ov == 0, f"block overflow: {ov}"
    assert total == half, (total, half)
    t_comb = _time(lambda a, b: comb(a, b)[0], (r, s))
    print(f"combined (scatter+batched+scan): {t_comb*1e3:6.2f} ms/iter")

    # stage decomposition
    pm = jax.jit(lambda a, b: _pack_pm(a, b, FANOUT_BITS))
    packed = jax.block_until_ready(pm(r, s))
    grp = jax.jit(lambda p: _group_blocks(p, capacity)[0])
    rows = jax.block_until_ready(grp(packed))
    t_grp = _time(grp, (packed,))
    rsort = jax.jit(
        lambda x: jax.lax.sort((x,), dimension=1, is_stable=False)[0])
    rows_sorted = jax.block_until_ready(rsort(rows))
    t_rsort = _time(rsort, (rows,))
    t_scan = _time(jax.jit(lambda x: _scan_count(x.reshape(-1))),
                   (rows_sorted,))
    print(f"  stage: group into blocks      {t_grp*1e3:8.2f} ms "
          f"(dest kv-sort + {nb} DMA runs)")
    print(f"  stage: batched row sort       {t_rsort*1e3:8.2f} ms")
    print(f"  stage: fused merge scan       {t_scan*1e3:8.2f} ms")

    delta = (t_flat - t_comb) / t_flat * 100.0
    verdict = ("WIN" if t_comb < t_flat * 0.85 else
               "no-win" if t_comb < t_flat else "DEAD-END")
    print(f"verdict: {verdict} — combined is {delta:+.1f}% vs flat "
          f"({t_comb*1e3:.2f} vs {t_flat*1e3:.2f} ms/iter)", flush=True)


if __name__ == "__main__":
    main()
