"""Forensics-bundle renderer/merger CLI (observability/postmortem.py).

    python tools_postmortem.py BUNDLE.json                # render one
    python tools_postmortem.py forensics/                 # render each
    python tools_postmortem.py forensics/ --merge         # fleet summary
    python tools_postmortem.py a.json b.json --merge --json

A *bundle* is the self-contained JSON a run emits on any terminal
failure, deadline expiry, breaker trip, watchdog trip, or chaos
violation: config fingerprint, JoinPlan, plan-vs-actual audit table,
flight-recorder ring, heartbeat tail, thread stacks, chaos ``(seed,
arms)``, env/backend info.  Rendering turns one bundle into a readable
report; ``--merge`` summarizes many (counts by reason/failure class/
rank, time range, one row per bundle) — the shape a fleet report wants
before anyone opens individual bundles.

Exits 0 on success, 1 when any input is unreadable, 2 on usage errors.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tpu_radix_join.observability.postmortem import (list_bundles,
                                                     load_bundle,
                                                     merge_bundles,
                                                     render_bundle)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools_postmortem.py",
        description="Render or merge post-mortem forensics bundles.")
    p.add_argument("paths", nargs="+",
                   help="bundle file(s) and/or directories of bundles")
    p.add_argument("--merge", action="store_true",
                   help="cross-bundle summary instead of per-bundle "
                        "rendering")
    p.add_argument("--json", action="store_true",
                   help="raw JSON output (merge summary, or the loaded "
                        "bundles)")
    p.add_argument("--ring-tail", type=int, default=20,
                   help="flight-recorder records to show per bundle "
                        "(default %(default)s)")
    p.add_argument("--no-stacks", action="store_true",
                   help="omit thread stacks from rendered output")
    return p


def _expand(paths) -> list:
    out = []
    for p in paths:
        if os.path.isdir(p):
            found = list_bundles(p)
            if not found:
                print(f"WARNING: no bundle_*.json under {p}",
                      file=sys.stderr)
            out.extend(found)
        else:
            out.append(p)
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    paths = _expand(args.paths)
    if not paths:
        print("error: no bundles to read", file=sys.stderr)
        return 2
    if args.merge:
        summary = merge_bundles(paths)
        if args.json:
            print(json.dumps(summary, indent=2))
        else:
            print(f"{summary['bundles']} bundle(s), "
                  f"{summary['t_first']} .. {summary['t_last']}")
            print(f"by reason:        {summary['by_reason']}")
            print(f"by failure class: {summary['by_failure_class']}")
            print(f"by rank:          {summary['by_rank']}")
            print(f"by mesh epoch:    {summary['by_membership_epoch']}")
            # fleet workers stamp w<slot>i<n> incarnation ids; a crash-
            # looping slot's bundles then read as one timeline per
            # incarnation.  Suppressed when nothing was stamped (every
            # bundle groups under "None" for non-fleet runs).
            incarn = summary.get("by_worker_incarnation") or {}
            if set(incarn) - {"None"}:
                print(f"by incarnation:   {incarn}")
            if summary["recovery_timeline"]:
                # grouped by membership epoch: every epoch's block reads
                # as one fencing story — what changed the membership
                # (loss/admission), the hedge fence claims written under
                # it, and the recovery that closed it
                print("recovery timeline:")
                by_epoch = {}
                for ev in summary["recovery_timeline"]:
                    by_epoch.setdefault(ev.get("epoch"), []).append(ev)
                for epoch in sorted(by_epoch,
                                    key=lambda e: (e is None, e)):
                    print(f"  membership epoch {epoch}:")
                    for ev in by_epoch[epoch]:
                        what = ev.get("event")
                        if what == "rank_lost":
                            detail = (f"lost={ev.get('ranks')} "
                                      f"cause={ev.get('cause')} "
                                      f"survivors={ev.get('survivors')}")
                        elif what == "rank_join":
                            detail = (f"admitted={ev.get('ranks')} "
                                      f"members={ev.get('members')}")
                        elif what == "hedge_claim":
                            detail = (f"partition={ev.get('partition')} "
                                      f"owner={ev.get('owner')}")
                        elif what == "hedge":
                            detail = (f"straggler={ev.get('straggler')} "
                                      f"progress={ev.get('progress')} "
                                      f"median={ev.get('median')} "
                                      f"outstanding="
                                      f"{ev.get('outstanding')}")
                        elif what == "straggle":
                            detail = (f"victim={ev.get('rank')} "
                                      f"factor={ev.get('factor')}")
                        elif what == "regrow":
                            detail = f"joined={ev.get('joined_ranks')}"
                        else:
                            detail = (f"resumed={ev.get('resumed')} "
                                      f"recomputed={ev.get('recomputed')} "
                                      f"matches={ev.get('matches')}")
                        print(f"    t={ev.get('t_epoch_s')} "
                              f"rank={ev.get('rank')} {what} {detail}")
            for row in summary["rows"]:
                if "error" in row:
                    print(f"  UNREADABLE {row['path']}: {row['error']}")
                    continue
                drift = (f" drift={row['drift_pct']}%"
                         if row.get("drift_pct") is not None else "")
                qid = (f" query={row['query_id']}"
                       if row.get("query_id") else "")
                tid = (f" trace={row['trace_id']}"
                       if row.get("trace_id") else "")
                mep = (f" epoch={row['membership_epoch']}"
                       if row.get("membership_epoch") is not None else "")
                winc = (f" incarnation={row['worker_incarnation']}"
                        if row.get("worker_incarnation") is not None else "")
                print(f"  {row['path']}: {row['reason']} "
                      f"[{row['failure_class']}] rank={row['rank']} "
                      f"strategy={row.get('strategy')}{drift}{qid}{tid}"
                      f"{mep}{winc}")
                # per-query critical-path breakdown: which rank's which
                # phase bounded this bundle's join, and how much of it
                # was waiting (rows without one cost nothing)
                cp = row.get("critical_path")
                if cp and not cp.get("error"):
                    f = cp.get("fractions") or {}
                    top = cp.get("top_phase") or {}
                    print(f"    critical path: {cp.get('path_ms')}ms "
                          f"bound=rank{cp.get('bounding_rank')} "
                          f"compute={f.get('compute', 0) * 100:.0f}% "
                          f"wait={f.get('collective_wait', 0) * 100:.0f}% "
                          f"straggle={f.get('straggle', 0) * 100:.0f}%"
                          + (f" top={top.get('name')}@"
                             f"r{top.get('rank')}:{top.get('ms')}ms"
                             if top else ""))
                    hedge = cp.get("hedge") or {}
                    if hedge.get("n_claims"):
                        saved = hedge.get("saved_ms_estimate")
                        print(f"    hedge: {hedge['n_claims']} claim(s)"
                              + (f", shortened path ~{saved}ms "
                                 f"({hedge.get('basis')})"
                                 if saved is not None else ""))
        bad = sum(1 for r in summary["rows"] if "error" in r)
        return 1 if bad else 0
    rc = 0
    for i, path in enumerate(paths):
        try:
            bundle = load_bundle(path)
        except (OSError, ValueError) as e:
            print(f"error: unreadable bundle {path}: {e!r}",
                  file=sys.stderr)
            rc = 1
            continue
        if args.json:
            print(json.dumps(bundle, indent=2))
            continue
        if i:
            print()
        print(f"# {path}")
        print(render_bundle(bundle, ring_tail=args.ring_tail,
                            stacks=not args.no_stacks))
    return rc


if __name__ == "__main__":
    sys.exit(main())
