#!/bin/bash
# Round-5 follow-on chip tasks, added while tools_run_chip_tasks.sh was
# already executing (a running bash script cannot be edited in place).
# Waits for ANY live primary-runner process to exit before starting, so the
# two never time 16M benchmarks concurrently through the one chip; then runs
# with the shared probe/retry/.done discipline into the same OUT dir.
#   * cli_16m_twolevel_fused — the bucket path WITHOUT --measure-phases:
#     the fused-truth number for the split-vs-fused gap analysis
#     (exp_phase_net.py; VERDICT r4 #7).
#   * cli_16m_full — the r5 full-range key discipline's measured cost
#     (--key-range full), priced against perf_16m_sort's packed path.
set -u
cd /root/repo
OUT=artifacts/chip_r5
source tools_chip_lib.sh

# Match the primary runner by SCRIPT NAME, not by invocation form: the old
# 'bash tools_run_chip_tasks.sh$' pattern let './tools_run_chip_tasks.sh',
# 'bash /root/repo/tools_run_chip_tasks.sh', or any trailing argument slip
# past the guard and time benchmarks concurrently through the one chip.
# This script's own cmdline never matches ("..._tasks_extra.sh" puts
# '_extra' where the pattern requires '.sh'), and our own PID is excluded
# anyway in case a caller ever embeds the primary's name in our argv.
while pgrep -f 'tools_run_chip_tasks\.sh' | grep -qvw "$$"; do
  sleep 60
done

SIXTEEN=$((1<<24))
run cli_16m_twolevel_fused 2400 python -m tpu_radix_join.main \
    --tuples-per-node $SIXTEEN --nodes 1 --two-level --repeat 3 \
    --output-dir "$OUT/perf_16m_twolevel_fused"
run cli_16m_full 2400 python -m tpu_radix_join.main \
    --tuples-per-node $SIXTEEN --nodes 1 --key-range full --repeat 3 \
    --output-dir "$OUT/perf_16m_full"
run cli_16m_pipelined 2400 python -m tpu_radix_join.main \
    --tuples-per-node $SIXTEEN --nodes 1 --repeat 20 --pipeline-repeats \
    --output-dir "$OUT/perf_16m_pipelined"
run trace_16m_twolevel 2400 python experiments/exp_trace_pipeline.py 24 \
    "$OUT/trace_16m_twolevel" --two-level
run cli_16m_full_pipelined 2400 python -m tpu_radix_join.main \
    --tuples-per-node $SIXTEEN --nodes 1 --key-range full --repeat 20 \
    --pipeline-repeats --output-dir "$OUT/perf_16m_full_pipelined"
echo "ALL_EXTRA_CHIP_TASKS_DONE $(date -u +%H:%M:%S)"
