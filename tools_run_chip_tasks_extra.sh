#!/bin/bash
# Round-5 follow-on chip tasks.  Kept out of tools_run_chip_tasks.sh because
# that script was already executing when these were added (bash reads a
# running script incrementally — editing it mid-run corrupts execution).
# Waits for the primary runner to finish (its pid or the final marker), then
# runs with the same probe/retry/.done discipline into the same OUT dir.
#   * cli_16m_twolevel_fused — the bucket path WITHOUT --measure-phases:
#     the fused-truth number for the split-vs-fused gap analysis
#     (exp_phase_net.py; VERDICT r4 #7).
#   * cli_16m_full — the r5 full-range key discipline's measured cost
#     (--key-range full), priced against perf_16m_sort's packed path.
set -u
cd /root/repo
export PYTHONPATH=/root/repo${PYTHONPATH:+:$PYTHONPATH}
OUT=artifacts/chip_r5
mkdir -p "$OUT"
MAX_ATTEMPTS=6
PRIMARY_PID=${1:-}

if [ -n "$PRIMARY_PID" ]; then
  while kill -0 "$PRIMARY_PID" 2>/dev/null; do
    sleep 60
  done
fi

probe() { timeout 60 python -c "import jax; print(jax.devices()[0])" >/dev/null 2>&1; }

wait_tunnel() {
  for i in $(seq 1 400); do
    if probe; then return 0; fi
    echo "$(date -u +%H:%M:%S) tunnel down, waiting..."
    sleep 90
  done
  echo "tunnel never came back"; return 1
}

run() {
  name=$1; shift
  tmo=$1; shift
  if [ -f "$OUT/$name.done" ]; then echo "=== $name: already done, skipping ==="; return 0; fi
  echo "=== $name: $* ==="
  for attempt in $(seq 1 $MAX_ATTEMPTS); do
    wait_tunnel || return 1
    timeout "$tmo" "$@" > "$OUT/$name.a$attempt.log" 2>&1
    rc=$?
    ln -sf "$name.a$attempt.log" "$OUT/$name.log"
    echo "$name attempt $attempt rc=$rc ($(date -u +%H:%M:%S))"
    if [ "$rc" = 0 ]; then touch "$OUT/$name.done"; return 0; fi
    sleep 30
  done
  echo "$name FAILED after $MAX_ATTEMPTS attempts"
  return 1
}

SIXTEEN=$((1<<24))
run cli_16m_twolevel_fused 2400 python -m tpu_radix_join.main \
    --tuples-per-node $SIXTEEN --nodes 1 --two-level --repeat 3 \
    --output-dir "$OUT/perf_16m_twolevel_fused"
run cli_16m_full 2400 python -m tpu_radix_join.main \
    --tuples-per-node $SIXTEEN --nodes 1 --key-range full --repeat 3 \
    --output-dir "$OUT/perf_16m_full"
echo "ALL_EXTRA_CHIP_TASKS_DONE $(date -u +%H:%M:%S)"
