"""Profile fit/refresh/diff CLI: turn ledger evidence into device profiles.

    python tools_profile_fit.py fit --ledger artifacts/ledger
    python tools_profile_fit.py fit --ledger artifacts/ledger \
        --base v5e_lite --out artifacts/ledger/profile_fitted.json \
        --min-samples 2
    python tools_profile_fit.py refresh --ledger artifacts/ledger
    python tools_profile_fit.py diff v5e_lite artifacts/ledger/profile_fitted.json

``fit`` robust-fits every REQUIRED_CONSTANT the ledger has enough samples
for (planner/calibrate.py) and writes a schema-v3 profile whose
per-constant provenance blocks cite run ids, sample count, 95% CI, fit
residual, and freshness; the default --out is the
``profile_fitted.json`` that ``--profile auto`` prefers while fresh.
Under-sampled fits are REFUSED (exit 2), never silently padded — a
profile that merely echoes its base under a ``fit`` label would poison
the provenance chain.

``refresh`` runs staleness detection (persistent PLANDRIFT attributed to
each drifting plan's dominant cost term) and re-fits; exit 1 when stale
constants were found (evidence the committed snapshot has aged), 0 when
the profile is clean.

``diff`` prints the per-constant relative-delta table between two
profiles (names or paths); exit 1 when any constant moved past
--threshold — the same exit discipline as tools_check_regress.py, so CI
can gate on "the fitted profile agrees with the committed one".
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from tpu_radix_join.observability.ledger import default_ledger_dir, load_rows
from tpu_radix_join.planner.calibrate import (DEFAULT_DRIFT_THRESHOLD_PCT,
                                              DEFAULT_MIN_PERSIST,
                                              DEFAULT_MIN_SAMPLES,
                                              UnderSampledError,
                                              detect_stale, diff_profiles,
                                              fit_profile)
from tpu_radix_join.planner.profile import (FITTED_PROFILE_BASENAME,
                                            ProfileError, format_provenance,
                                            load_profile)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="tools_profile_fit.py",
        description="Fit, refresh, or diff device profiles from a "
                    "telemetry ledger.")
    sub = p.add_subparsers(dest="cmd", required=True)

    def ledger_args(sp):
        sp.add_argument("--ledger", default=None, metavar="DIR_OR_FILE",
                        help="ledger dir or .jsonl (default: "
                             "$TPU_RADIX_LEDGER_DIR or artifacts/ledger)")
        sp.add_argument("--base", default="v5e_lite",
                        help="base profile name/path for unfitted constants "
                             "(default %(default)s)")
        sp.add_argument("--min-samples", type=int,
                        default=DEFAULT_MIN_SAMPLES,
                        help="refuse to fit a constant from fewer samples "
                             "(default %(default)s)")

    f = sub.add_parser("fit", help="fit a profile from ledger samples")
    ledger_args(f)
    f.add_argument("--out", default=None,
                   help="output profile path (default "
                        f"<ledger dir>/{FITTED_PROFILE_BASENAME})")
    f.add_argument("--name", default=None, help="fitted profile name")

    r = sub.add_parser("refresh",
                       help="detect stale constants and re-fit")
    ledger_args(r)
    r.add_argument("--out", default=None,
                   help="output profile path (default "
                        f"<ledger dir>/{FITTED_PROFILE_BASENAME})")
    r.add_argument("--name", default=None, help="fitted profile name")
    r.add_argument("--drift-threshold", type=float,
                   default=DEFAULT_DRIFT_THRESHOLD_PCT,
                   help="PLANDRIFT percent that counts as a miss "
                        "(default %(default)s)")
    r.add_argument("--min-persist", type=int, default=DEFAULT_MIN_PERSIST,
                   help="misses before a constant is stale "
                        "(default %(default)s)")

    d = sub.add_parser("diff", help="per-constant delta between profiles")
    d.add_argument("a", help="profile name or path (reference)")
    d.add_argument("b", help="profile name or path (candidate)")
    d.add_argument("--threshold", type=float, default=0.25,
                   help="relative delta past which exit is 1 "
                        "(default %(default)s)")
    return p


def _resolve_ledger(args) -> str:
    return args.ledger or default_ledger_dir()


def _fit(args, stale=None) -> int:
    ledger = _resolve_ledger(args)
    rows = load_rows(ledger)
    if not rows:
        print(f"error: no ledger rows at {ledger}", file=sys.stderr)
        return 2
    try:
        base = load_profile(args.base)
        prof, fits = fit_profile(rows, base=base, name=args.name,
                                 min_samples=args.min_samples)
    except UnderSampledError as e:
        print(f"error: under-sampled fit refused: {e}", file=sys.stderr)
        return 2
    except (ProfileError, OSError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    out = args.out or os.path.join(
        ledger if not ledger.endswith(".jsonl") else os.path.dirname(ledger),
        FITTED_PROFILE_BASENAME)
    try:
        prof.save(out)
    except OSError as e:
        print(f"error: cannot write {out}: {e}", file=sys.stderr)
        return 2
    print(f"fitted {len(fits)}/{len(prof.constants)} constants from "
          f"{len(rows)} ledger rows -> {out}")
    print(format_provenance(prof, stale=stale))
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "fit":
        return _fit(args)
    if args.cmd == "refresh":
        ledger = _resolve_ledger(args)
        stale = detect_stale(load_rows(ledger),
                             threshold_pct=args.drift_threshold,
                             min_persist=args.min_persist)
        rc = _fit(args, stale=stale)
        if rc != 0:
            return rc
        if stale:
            names = ", ".join(sorted(stale))
            print(f"stale constants re-fit: {names}")
            return 1            # evidence found: the old profile had aged
        print("no stale constants")
        return 0
    # diff
    try:
        a, b = load_profile(args.a), load_profile(args.b)
    except ProfileError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    rows = diff_profiles(a, b)
    worst = 0.0
    print(f"{'constant':<24} {'a (' + a.name + ')':>20} "
          f"{'b (' + b.name + ')':>20} {'rel_delta':>10}")
    for r in rows:
        rel = r["rel_delta"]
        worst = max(worst, rel or 0.0)
        print(f"{r['constant']:<24} "
              f"{r['a'] if r['a'] is not None else '-':>20} "
              f"{r['b'] if r['b'] is not None else '-':>20} "
              f"{f'{rel:.1%}' if rel is not None else '-':>10}")
    if worst > args.threshold:
        print(f"max relative delta {worst:.1%} exceeds "
              f"--threshold {args.threshold:.1%}")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
